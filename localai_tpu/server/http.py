"""The OpenAI-compatible HTTP server (L5) — aiohttp.

Surface mirrors the reference routes (/root/reference/core/http/routes/
openai.go:13-181 + localai.go): /v1/chat/completions (SSE streaming loop like
chat.go:334-449), /v1/completions, /v1/embeddings, /v1/models, rerank,
tokenize, Prometheus /metrics, health. The RequestExtractor middleware
semantics (request.go:118-211) live in `_merged_options`: per-request JSON
fields override the model YAML's `parameters:` defaults.

gRPC backends are synchronous; unary calls run in the default executor and
streams are bridged thread→asyncio.Queue so one slow model never blocks the
event loop.
"""
from __future__ import annotations

import asyncio
import contextlib
import json
import os
import threading
import time

import grpc
from aiohttp import web

from localai_tpu import telemetry
from localai_tpu.config import AppConfig, ModelConfig, ModelConfigLoader
from localai_tpu.core import resilience
from localai_tpu.core.manager import ModelManager
from localai_tpu.server import schema
from localai_tpu.testing.lockdep import lockdep_lock

try:
    from prometheus_client import (
        CONTENT_TYPE_LATEST, Counter, Gauge, Histogram, REGISTRY,
        generate_latest,
    )
    from prometheus_client.core import HistogramMetricFamily

    _API_CALLS = Counter("localai_api_calls_total", "API calls",
                         ["path", "status"])
    _API_LATENCY = Histogram("localai_api_latency_seconds", "API latency",
                             ["path"])
    # engine-stage series (telemetry subsystem): refreshed from each loaded
    # backend's GetMetrics prof_* keys at scrape time (LOCALAI_PROFILE
    # runs). These are cumulative, so they are COUNTERS (ISSUE 11 satellite:
    # they were Gauges despite the _total suffix); prometheus_client strips
    # and re-appends the suffix, so the exposed series names are unchanged.
    # Scrape-side .set() semantics are recovered by inc-ing the delta
    # against the last scraped value (_counter_sync).
    _STAGE_SECONDS = Counter(
        "localai_engine_stage_seconds_total",
        "Cumulative fenced time per engine stage", ["model", "stage"])
    _STAGE_DISPATCHES = Counter(
        "localai_engine_stage_dispatches_total",
        "Cumulative dispatch count per engine stage", ["model", "stage"])
    # tokens/s is a last-value rate — legitimately a Gauge
    _STAGE_TOK_S = Gauge(
        "localai_engine_stage_tokens_per_second",
        "Tokens/s through each engine stage", ["model", "stage"])
    # load shedding (ISSUE 4): every 429/503 the admission layer or the
    # drain path produces is counted here so shedding is observable
    _SHED = Counter("localai_shed_total",
                    "Requests shed by admission control or drain",
                    ["model", "reason"])
    # preemption-safe serving (ISSUE 19): mid-stream resumes by outcome —
    # "ok" (the resumed stream produced its next chunk), "error" (every
    # resume lane failed and the terminal SSE error surfaced), "replay"
    # (deterministic re-issue with prompt+emitted, resume lane disabled)
    _RESUME = Counter("localai_resume_total",
                      "Mid-stream preemption resumes", ["model", "outcome"])
    # backend supervision events (spawn retries, respawns, watchdog reaps,
    # breaker rejections) — refreshed from ModelManager.events at scrape;
    # cumulative event counts → Counter (was a mis-typed Gauge)
    _SUPERVISION = Counter("localai_backend_supervision_total",
                           "Backend supervision events", ["model", "event"])
    # scheduler X-ray (ISSUE 13): tick-ledger series refreshed from each
    # backend's GetMetrics sched_* keys at scrape time
    _SCHED_REASONS = Counter(
        "localai_sched_reason_total",
        "Scheduler decisions by registered reason code", ["model", "code"])
    _SCHED_DISPATCHES = Counter(
        "localai_sched_dispatches_total",
        "Engine dispatches by compiled program variant",
        ["model", "variant"])
    _SCHED_TICKS = Counter(
        "localai_sched_ticks_total", "Engine scheduler ticks", ["model"])
    _SCHED_UTIL = Gauge(
        "localai_sched_budget_utilization",
        "Fraction of the ragged token budget carrying live tokens",
        ["model"])
    _SCHED_PAD = Gauge(
        "localai_sched_pad_rows_frac",
        "Fraction of allocated dispatch rows that were padding", ["model"])
    # host-RAM KV tier (ISSUE 17): pool occupancy is a level (Gauge);
    # spill/hit/eviction totals are cumulative (Counter via _counter_sync)
    _KV_HOST = Gauge(
        "localai_kv_host", "Host KV tier occupancy",
        ["model", "stat"])
    # NOTE: the counter family must not share the Gauge's base name —
    # prometheus_client strips the _total suffix at registration, so
    # "localai_kv_host_total" would collide with the Gauge above
    _KV_HOST_EVENTS = Counter(
        "localai_kv_host_events_total", "Host KV tier cumulative events",
        ["model", "event"])
    # last cumulative value each counter child was synced to, keyed by the
    # label tuple — a backend restart resets its counters, which _counter_sync
    # treats as a fresh start (standard Prometheus counter-reset semantics)
    _COUNTER_LAST: dict = {}

    def _counter_sync(counter, labels: tuple, value: float):
        """Bring a scrape-fed Counter child to an absolute cumulative value
        by inc-ing the delta (Counter has no .set, by design)."""
        key = (counter, labels)
        last = _COUNTER_LAST.get(key, 0.0)
        if value < last:     # source restarted: its series began again
            last = 0.0
        if value > last:
            counter.labels(*labels).inc(value - last)
            _COUNTER_LAST[key] = value
        elif key not in _COUNTER_LAST:
            counter.labels(*labels)   # materialize the child at 0
            _COUNTER_LAST[key] = value

    # latest per-model SLO histograms, refreshed at scrape from each
    # backend's GetMetrics hist_* keys (telemetry.metrics.parse_flat);
    # exposed as TRUE Prometheus histogram series by _SLOCollector
    _SLO_SCRAPE: dict = {}

    class _SLOCollector:
        """Custom collector rebuilding localai_request_<metric>_seconds
        histogram series (_bucket/_sum/_count, labels model+path) from the
        scraped engine histograms — prometheus_client's Histogram cannot be
        set to absolute bucket counts, a raw MetricFamily can."""

        def collect(self):
            fams = {}
            for model, hists in list(_SLO_SCRAPE.items()):
                for (metric, path), h in hists.items():
                    fam = fams.get(metric)
                    if fam is None:
                        fam = fams[metric] = HistogramMetricFamily(
                            f"localai_request_{metric}_seconds",
                            f"Per-request {metric} latency",
                            labels=["model", "path"])
                    acc, buckets = 0, []
                    for i, ub in enumerate(telemetry.BUCKETS_S):
                        acc += h.counts[i]
                        le = "+Inf" if ub == float("inf") else repr(ub)
                        buckets.append((le, acc))
                    fam.add_metric([model, path], buckets, h.sum)
            return list(fams.values())

    REGISTRY.register(_SLOCollector())
    _HAVE_PROM = True
except Exception:  # pragma: no cover - prometheus_client is in the image
    _HAVE_PROM = False

_OPEN_PATHS = {"/healthz", "/readyz", "/metrics"}

# sampling fields copied request-JSON → PredictOptions when present
_SAMPLING_FIELDS = (
    "temperature", "top_k", "top_p", "min_p", "typical_p", "repeat_penalty",
    "presence_penalty", "frequency_penalty", "seed", "ignore_eos",
)


_IMAGE_FETCH_LIMIT = 16 << 20   # 16 MiB of image bytes per URL


def _engine_timings(reply) -> dict:
    """The engine's per-request phase timeline (Reply.timings_json, set on
    the FINAL reply only) → the llama.cpp-style `timings` block: queued→
    admitted→first_token→finished ms, decode path, dispatch count."""
    raw = getattr(reply, "timings_json", "")
    if not raw:
        return {}
    try:
        t = json.loads(raw)
    except ValueError:
        return {}
    return t if isinstance(t, dict) else {}


def _fetch_image(url: str) -> str:
    """Fetch a remote image_url → base64, with the two server-side hazards
    closed: a size cap (the body is b64-expanded into the request pipeline)
    and an SSRF guard (no loopback/link-local/private targets — a chat
    request must not become a probe of the server's network)."""
    import base64
    import ipaddress
    import socket
    import urllib.parse
    import urllib.request

    host = urllib.parse.urlparse(url).hostname or ""
    try:
        infos = socket.getaddrinfo(host, None)
    except OSError as e:
        raise ValueError(f"cannot resolve image host {host!r}: {e}")
    for info in infos:
        ip = ipaddress.ip_address(info[4][0])
        if (ip.is_private or ip.is_loopback or ip.is_link_local
                or ip.is_reserved or ip.is_multicast):
            raise ValueError(f"image host {host!r} resolves to a "
                             f"non-public address")
    with urllib.request.urlopen(url, timeout=30) as r:
        data = r.read(_IMAGE_FETCH_LIMIT + 1)
    if len(data) > _IMAGE_FETCH_LIMIT:
        raise ValueError(f"image at {host!r} exceeds "
                         f"{_IMAGE_FETCH_LIMIT >> 20} MiB")
    return base64.b64encode(data).decode()


class _AdmissionGate:
    """Per-model admission state: `limit` concurrent requests against the
    backend plus at most `depth` waiters; the rest shed with 429."""

    def __init__(self, limit: int, depth: int):
        self.limit = max(1, int(limit))
        self.depth = max(0, int(depth))
        self.sem = asyncio.Semaphore(self.limit)
        self.waiting = 0


class API:
    def __init__(self, app_config: AppConfig, configs: ModelConfigLoader,
                 manager: ModelManager):
        self.cfg = app_config
        self.configs = configs
        self.manager = manager
        # KV-affinity gossip (ISSUE 17): text-chain ids of every chat/
        # completion conversation this worker served, reported via /healthz
        # so the federation picker routes follow-up turns here. Maintained
        # unconditionally — it is a bounded dict of hex strings; the
        # federation layer decides whether anyone listens.
        from localai_tpu.engine.kvhost import PrefixDigest

        self._kv_served = PrefixDigest(cap=2048)
        self.app = web.Application(middlewares=[self._middleware],
                                   client_max_size=app_config.max_request_bytes)
        r = self.app.router
        r.add_get("/healthz", self._health)
        r.add_get("/readyz", self._health)
        r.add_get("/metrics", self._metrics)
        r.add_get("/v1/models", self._models)
        r.add_get("/models", self._models)
        r.add_post("/v1/chat/completions", self._chat)
        r.add_post("/chat/completions", self._chat)
        r.add_post("/v1/completions", self._completions)
        r.add_post("/completions", self._completions)
        r.add_post("/v1/edits", self._edits)
        # MCP agentic chat (reference endpoints/openai/mcp.go:1-142)
        r.add_post("/mcp/v1/chat/completions", self._mcp_chat)
        r.add_post("/mcp/v1/completions", self._mcp_chat)
        r.add_post("/v1/embeddings", self._embeddings)
        r.add_post("/embeddings", self._embeddings)
        r.add_post("/v1/rerank", self._rerank)
        r.add_post("/rerank", self._rerank)
        r.add_post("/v1/detection", self._detection)
        r.add_post("/v1/tokenize", self._tokenize)
        r.add_post("/tokenize", self._tokenize)
        r.add_get("/v1/realtime", self._realtime)
        r.add_post("/v1/realtime/sessions", self._realtime_session)
        r.add_post("/v1/realtime/transcription_session",
                   self._realtime_transcription_session)
        r.add_post("/v1/images/generations", self._images)
        r.add_post("/v1/videos", self._videos)
        r.add_post("/video", self._videos)
        r.add_post("/v1/audio/transcriptions", self._transcriptions)
        r.add_post("/v1/audio/speech", self._speech)
        r.add_post("/tts", self._speech)
        r.add_post("/vad", self._vad)
        r.add_post("/sound-generation", self._sound_generation)
        # telemetry debug surface (ISSUE 2): merged Chrome trace + per-model
        # stage profile across the HTTP process and every backend subprocess
        r.add_get("/debug/trace", self._debug_trace)
        r.add_get("/debug/profile", self._debug_profile)
        # SLO observability (ISSUE 11): percentile snapshot per model+path
        # and the crash flight recorder (recent request timelines, engine
        # ticks, tripwire/breaker/supervision events)
        r.add_get("/debug/slo", self._debug_slo)
        r.add_get("/debug/flightrec", self._debug_flightrec)
        # scheduler X-ray (ISSUE 13): per-tick pack ledger, reason-code
        # counters, and per-variant cost-analysis rooflines
        r.add_get("/debug/sched", self._debug_sched)
        r.add_get("/backend/monitor", self._backend_monitor)
        r.add_post("/backend/shutdown", self._backend_shutdown)
        # explicit preemption notice (ISSUE 19): spill-drain the model's
        # backend into resume checkpoints instead of draining to completion
        r.add_post("/backend/preempt", self._backend_preempt)
        r.add_get("/system", self._system)
        r.add_post("/stores/set", self._stores_set)
        r.add_post("/stores/get", self._stores_get)
        r.add_post("/stores/delete", self._stores_delete)
        r.add_post("/stores/find", self._stores_find)
        r.add_post("/models/apply", self._models_apply)
        r.add_get("/models/available", self._models_available)
        r.add_get("/models/jobs/{job_id}", self._models_job)
        # backend gallery (reference routes/localai.go:53-58)
        r.add_get("/backends", self._backends_list)
        r.add_get("/backends/available", self._backends_available)
        r.add_get("/backends/galleries", self._backends_galleries)
        r.add_post("/backends/apply", self._backends_apply)
        r.add_post("/backends/delete/{name}", self._backends_delete)
        r.add_get("/backends/jobs/{job_id}", self._backends_job)
        # WebUI (reference routes/ui.go role) + API-compat route families
        r.add_get("/", self._webui)
        r.add_get("/chat", self._webui)
        # elevenlabs compat (reference routes/elevenlabs.go)
        r.add_post("/v1/text-to-speech/{voice_id}", self._elevenlabs_tts)
        r.add_post("/v1/sound-generation", self._sound_generation)
        self.gallery_service = None  # wired by run_server when galleries set
        self.backend_gallery_service = None  # ditto (backend registry)
        self._mcp_sessions: dict[str, list] = {}   # model → MCP sessions
        self._mcp_lock = lockdep_lock("http.mcp")
        # resilience state (ISSUE 4): per-model admission gates, the drain
        # flag the middleware turns into 503s, and the live-request count
        # graceful shutdown waits on
        self._gates: dict[str, _AdmissionGate] = {}
        self._draining = False
        self._inflight = 0
        # SIGTERM → web.run_app GracefulExit → runner.cleanup → here:
        # drain in-flight work instead of reaping backends mid-generation
        self.app.on_shutdown.append(self._on_shutdown)

    # ------------------------------------------------------------ middleware

    async def _federation_ok(self, request: web.Request) -> bool:
        """A valid shared-token HMAC signature (federation/auth.py — the
        reference's p2p token role, p2p.go:31-66) authorizes a request like
        an API key: that's how a federation LB reaches api-key-protected
        workers without distributing the keys."""
        if not getattr(self.cfg, "federation_token", ""):
            return False
        from localai_tpu.federation.auth import HEADER, verify

        header = request.headers.get(HEADER)
        if not header:
            return False
        body = await request.read()   # aiohttp caches; handlers re-read
        return verify(self.cfg.federation_token, header, request.method,
                      request.path_qs, body)

    @web.middleware
    async def _middleware(self, request: web.Request, handler):
        t0 = time.perf_counter()
        status = 500
        # request-id propagation root: honor a caller-supplied X-Request-Id,
        # mint one otherwise; the contextvar follows this request through the
        # handler (and asyncio.to_thread copies the context) into the gRPC
        # client's x-localai-request-id metadata → backend → engine spans
        rid = request.headers.get("X-Request-Id") or telemetry.new_request_id()
        rid_token = telemetry.set_request_id(rid)
        # work requests are counted for graceful drain and carry a deadline
        # budget; /backend/shutdown and /backend/preempt stay admitted
        # (they DRIVE the drain / spill-drain)
        counted = (request.path not in _OPEN_PATHS
                   and request.path not in ("/backend/shutdown",
                                            "/backend/preempt"))
        dl_token = None
        try:
            if self.cfg.api_keys and request.path not in _OPEN_PATHS:
                auth = request.headers.get("Authorization", "")
                key = auth.removeprefix("Bearer ").strip()
                if key not in self.cfg.api_keys and not (
                        await self._federation_ok(request)):
                    status = 401
                    return web.json_response(
                        schema.error_body("invalid api key",
                                          "authentication_error", 401),
                        status=401)
            if self._draining and counted:
                # graceful shutdown in progress: shed new work loudly so the
                # LB moves on, while in-flight requests finish
                status = 503
                if _HAVE_PROM:
                    _SHED.labels("-", "draining").inc()
                return web.json_response(
                    schema.error_body("server is draining; retry elsewhere",
                                      "server_error", 503),
                    status=503, headers={"Retry-After": "1",
                                         "X-Request-Id": rid})
            # per-request deadline budget (ISSUE 4): middleware-minted,
            # contextvar-carried — the gRPC client shrinks its timeouts to
            # the remainder and ships it in-band so the engine can evict an
            # expired slot. X-Request-Timeout may only LOWER the app bound.
            budget = float(getattr(self.cfg, "request_timeout", 600.0) or 0)
            hdr = request.headers.get("X-Request-Timeout", "")
            if hdr:
                try:
                    v = float(hdr)
                    if v > 0:
                        budget = min(budget, v) if budget else v
                except ValueError:
                    pass
            if counted and budget > 0:
                dl_token = resilience.set_deadline(budget)
            if counted:
                self._inflight += 1
            try:
                resp = await handler(request)
            finally:
                if counted:
                    self._inflight -= 1
            status = resp.status
            if self.cfg.machine_tag:  # fleet tracking (app.go:93-100)
                resp.headers["Machine-Tag"] = self.cfg.machine_tag
            resp.headers["X-Request-Id"] = rid
            return resp
        except web.HTTPException as e:
            status = e.status
            e.headers["X-Request-Id"] = rid
            raise
        except resilience.ResilienceError as e:
            # typed serving failures (supervisor, breaker, admission,
            # deadline) carry their own HTTP translation + Retry-After
            status = e.status
            if _HAVE_PROM and isinstance(e, resilience.RequestShed):
                _SHED.labels(e.model or "-", e.reason or "overload").inc()
            headers = {"X-Request-Id": rid}
            if e.retry_after:
                headers["Retry-After"] = str(max(int(e.retry_after + 0.999),
                                                 1))
            kind = {429: "overloaded_error", 503: "server_error",
                    504: "timeout_error"}.get(status, "server_error")
            return web.json_response(
                schema.error_body(str(e), kind, status),
                status=status, headers=headers)
        except grpc.RpcError as e:
            # untranslated gRPC stragglers: deadline → 504, severed/refused
            # channel → 502 (the supervisor normally converts these first)
            code = e.code() if hasattr(e, "code") else None
            status = {grpc.StatusCode.DEADLINE_EXCEEDED: 504,
                      grpc.StatusCode.UNAVAILABLE: 502,
                      grpc.StatusCode.INVALID_ARGUMENT: 400,
                      grpc.StatusCode.CANCELLED: 499}.get(code, 500)
            return web.json_response(
                schema.error_body(f"backend rpc failed: {code}",
                                  "server_error", status),
                status=status, headers={"X-Request-Id": rid})
        except Exception as e:
            status = 500
            return web.json_response(
                schema.error_body(f"{type(e).__name__}: {e}", "server_error",
                                  500), status=500,
                headers={"X-Request-Id": rid})
        finally:
            if dl_token is not None:
                resilience.reset_deadline(dl_token)
            tr = telemetry.maybe_tracer()
            if tr is not None and request.path not in _OPEN_PATHS:
                tr.add_complete(f"http {request.path}", t0, cat="http",
                                args={"request_id": rid, "status": status,
                                      "method": request.method})
            telemetry.reset_request_id(rid_token)
            if _HAVE_PROM:
                _API_CALLS.labels(request.path, str(status)).inc()
                _API_LATENCY.labels(request.path).observe(
                    time.perf_counter() - t0)

    # ------------------------------------------------------------ helpers

    def _resolve(self, body: dict) -> ModelConfig:
        """Model-name defaulting + config resolve (request.go:87-117)."""
        name = body.get("model") or ""
        cfg = self.configs.get(name) if name else self.configs.first()
        if cfg is None:
            raise web.HTTPNotFound(
                text=json.dumps(schema.error_body(
                    f"model {name!r} not found", code=404)),
                content_type="application/json")
        return cfg

    async def _handle(self, cfg: ModelConfig):
        try:
            return await asyncio.to_thread(self.manager.load, cfg)
        except resilience.ResilienceError:
            raise   # middleware translates (503 + Retry-After etc.)
        except Exception as e:
            raise web.HTTPInternalServerError(
                text=json.dumps(schema.error_body(
                    f"backend load failed: {e}", "server_error", 500)),
                content_type="application/json")

    def _gate(self, cfg: ModelConfig) -> "_AdmissionGate":
        g = self._gates.get(cfg.name)
        if g is None:
            g = self._gates[cfg.name] = _AdmissionGate(
                cfg.parallel or self.cfg.parallel_requests,
                getattr(self.cfg, "queue_depth", 8))
        return g

    @contextlib.asynccontextmanager
    async def _admit(self, cfg: ModelConfig):
        """Admission control (ISSUE 4): bounded per-model in-flight plus a
        small bounded wait queue; past that, fail FAST with 429 +
        Retry-After (counted in localai_shed_total) instead of stacking
        unbounded work on an overloaded engine."""
        gate = self._gate(cfg)
        if gate.sem.locked() and gate.waiting >= gate.depth:
            raise resilience.RequestShed(
                f"model {cfg.name!r} is at capacity "
                f"({gate.limit} in flight, {gate.waiting} queued)",
                model=cfg.name, reason="queue_full", retry_after=1.0)
        gate.waiting += 1
        try:
            rem = resilience.deadline_remaining()
            try:
                await asyncio.wait_for(gate.sem.acquire(), timeout=rem)
            except (asyncio.TimeoutError, TimeoutError):
                raise resilience.RequestShed(
                    f"model {cfg.name!r}: request deadline expired while "
                    f"queued for a slot",
                    model=cfg.name, reason="queue_timeout", retry_after=1.0)
        finally:
            gate.waiting -= 1
        try:
            yield
        finally:
            gate.sem.release()

    async def _unary(self, cfg: ModelConfig, method: str,
                     timeout: float = 600.0, **kw):
        """Supervised, cancellable unary RPC against `cfg`'s backend: the
        manager retries dead/UNAVAILABLE backends (respawning under the
        circuit breaker) since no bytes have reached the client yet, and a
        client disconnect cancels the in-flight RPC — the unary analog of
        the stream path's call.cancel()."""
        box: dict = {}

        def op(handle):
            fut = handle.client.start(method, timeout=timeout, **kw)
            box["fut"] = fut
            return fut.result()

        try:
            return await asyncio.to_thread(self.manager.supervised, cfg, op)
        except asyncio.CancelledError:
            fut = box.get("fut")
            if fut is not None:
                fut.cancel()
            raise

    def _merged_options(self, cfg: ModelConfig, body: dict) -> dict:
        """request JSON > model YAML defaults (request.go:118-211)."""
        p = cfg.parameters
        opts: dict = {}
        for f in _SAMPLING_FIELDS:
            v = body.get(f, getattr(p, f, None))
            if v is not None:
                opts[f] = v
        max_tokens = body.get("max_tokens", body.get("max_completion_tokens",
                                                     p.max_tokens))
        if max_tokens:
            opts["tokens"] = int(max_tokens)
        stop = body.get("stop", None)
        if stop is None:
            stop = list(cfg.stopwords)
        elif isinstance(stop, str):
            stop = [stop]
        if stop:
            opts["stop_prompts"] = stop
        bias = body.get("logit_bias", p.logit_bias)
        if bias:
            opts["logit_bias"] = {int(k): float(v) for k, v in bias.items()}
        if cfg.grammar:
            opts["grammar"] = cfg.grammar
        if body.get("response_format") or body.get("tools"):
            # grammar-constrained decoding wiring (functions/grammars)
            from localai_tpu.functions import grammar_for_request

            g = grammar_for_request(body)
            if g:
                opts["grammar"] = g
        if body.get("logprobs"):
            opts["logprobs"] = True
        return opts

    def _resume_enabled(self, cfg: ModelConfig) -> bool:
        """The ungraceful-death resume lane rides the host KV tier (ISSUE
        17): a model without a pool budget keeps the PR 4 contract (terminal
        SSE error once bytes have streamed), modulo the deterministic-replay
        fallback."""
        return bool(getattr(cfg, "kv_host_bytes", 0)
                    or getattr(self.cfg, "kv_host_bytes", 0))

    async def _stream_rpc(self, cfg: ModelConfig, opts: dict):
        """Supervised streaming call with mid-stream resume (ISSUE 19).

        Attempts that fail before ANY chunk reached the client retry
        transparently on a (re)spawned backend with capped backoff. Once
        bytes have streamed, three lanes run before the failure surfaces as
        the terminal SSE error event:

        - graceful preemption: a terminal ``finish_reason="preempted"``
          reply carries the engine's full spill-drain ResumeToken; the
          bridge swallows it, waits out the dying backend, and re-issues
          the RPC with the token — the respawned engine re-admits the
          checkpoint (host-pool hit or re-prefill) and the client sees one
          uninterrupted stream;
        - ungraceful death with the host KV tier enabled: the bridge
          synthesizes a token from its own accumulated state (prompt ids
          from the first chunk's minimal checkpoint, emitted ids, sent
          chars) and resumes the same way;
        - deterministic replay (resume lane disabled): temperature-0
          requests without tools/stop re-issue with ``prompt+emitted`` as
          the new prompt, holding back a short verification tail whose
          replayed tokens must match what the client already received —
          a divergent prefix falls back to the terminal error event.
        """
        retries = max(0, getattr(self.cfg, "retry_budget", 1))
        resume_budget = max(2, retries + 1)
        prompt_ids: list[int] = [int(t) for t in opts.get("prompt_ids") or []]
        emitted: list[int] = []      # every token id forwarded downstream
        sent_chars = 0               # every text char forwarded downstream
        orig_pt = 0                  # the ORIGINAL request's prompt_tokens
        base_tokens = 0              # generated count folded into resumes
        suppress: list[int] = []     # replay verification tail (determ. lane)
        ckpt: dict | None = None     # full spill-drain ResumeToken
        resumes = attempt = 0
        unconfirmed = ""             # resume mode awaiting its first chunk
        cur = opts
        while True:
            if attempt:
                await asyncio.sleep(resilience.backoff(attempt))
            handle = await self._handle(cfg)
            handle.mark_busy()
            streamed = bool(emitted or sent_chars)
            preempted = False
            err: Exception | None = None
            pump = self._pump_stream(handle, cur)
            try:
                async for reply in pump:
                    if reply.resume_json:
                        try:
                            d = json.loads(reply.resume_json)
                        except ValueError:
                            d = {}
                        if reply.finish_reason == "preempted":
                            ckpt = d or None
                        elif d.get("prompt_ids") and not prompt_ids:
                            # minimal first-chunk checkpoint: the tokenized
                            # prompt the resume lanes rebuild prompts from
                            prompt_ids = [int(t) for t in d["prompt_ids"]]
                    if unconfirmed:
                        if _HAVE_PROM and unconfirmed == "resume":
                            _RESUME.labels(cfg.name, "ok").inc()
                        unconfirmed = ""
                    if reply.finish_reason == "preempted":
                        # swallowed, never forwarded: the resume lane
                        # continues the stream from the checkpoint
                        preempted = True
                        break
                    if suppress:
                        # deterministic replay: the verification tail streams
                        # again first; the client already has these tokens,
                        # so they are swallowed — and they must MATCH, or the
                        # replay diverged and the stream cannot be resumed
                        diverged = bool(reply.finish_reason)
                        for t in reply.token_ids:
                            if not suppress or suppress.pop(0) != int(t):
                                diverged = True
                                break
                        if diverged:
                            raise RuntimeError(
                                f"deterministic replay diverged for "
                                f"{cfg.name!r}; cannot resume the stream")
                        continue
                    streamed = True
                    for t in reply.token_ids:
                        emitted.append(int(t))
                    sent_chars += len(reply.message.decode("utf-8",
                                                           "replace"))
                    if reply.prompt_tokens:
                        if orig_pt:
                            reply.prompt_tokens = orig_pt
                        elif not resumes:
                            orig_pt = reply.prompt_tokens
                    if base_tokens and reply.tokens:
                        reply.tokens += base_tokens
                    yield reply
                if not preempted:
                    return
            except grpc.RpcError as e:
                retriable, terr = await asyncio.to_thread(
                    self.manager.classify_failure, handle, e)
                if not streamed:
                    if retriable and attempt < retries:
                        attempt += 1
                        self.manager.events[(cfg.name, "stream_retry")] += 1
                        continue
                    raise terr from e
                err = terr
            finally:
                await pump.aclose()
                handle.mark_idle()
            if preempted:
                # wait out the dying backend before respawning, so the
                # resume never lands on an engine that is mid-drain
                await asyncio.to_thread(self.manager.preempt_model, cfg.name)
            nxt = None
            if resumes < resume_budget:
                nxt = self._resume_opts(cfg, opts, prompt_ids, emitted,
                                        sent_chars, ckpt)
            if nxt is None:
                if _HAVE_PROM and (resumes or ckpt is not None):
                    _RESUME.labels(cfg.name, "error").inc()
                if err is None:
                    err = resilience.BackendUnavailable(
                        f"backend for {cfg.name!r} was preempted mid-stream "
                        f"and the request could not be resumed")
                raise err
            cur, mode, suppress, base_tokens = nxt
            ckpt = None
            resumes += 1
            unconfirmed = mode
            if _HAVE_PROM and mode == "replay":
                _RESUME.labels(cfg.name, "replay").inc()
            self.manager.events[(cfg.name, f"stream_{mode}")] += 1
            telemetry.flightrec().record_event(
                "resume", model=cfg.name, mode=mode, emitted=len(emitted),
                sent_chars=sent_chars, resumes=resumes)

    def _resume_opts(self, cfg: ModelConfig, opts: dict,
                     prompt_ids: list[int], emitted: list[int],
                     sent_chars: int, ckpt: dict | None):
        """Build the re-issued request for a mid-stream resume, or None when
        no lane applies. Returns (opts, mode, suppress_tail, base_tokens)."""
        if "images" in opts:
            # multimodal KV is never frozen (engine skips mm slots) and the
            # projector embeds can't be rebuilt from token ids alone
            return None
        ropts = {k: v for k, v in opts.items()
                 if k not in ("prompt", "messages_json",
                              "use_tokenizer_template", "tools_json")}
        orig_tokens = int(opts.get("tokens") or 128)
        if ckpt is not None:
            # graceful spill-drain checkpoint: engine-authoritative
            ropts["prompt_ids"] = ([int(t) for t in ckpt["prompt_ids"]]
                                   + [int(t) for t in ckpt["emitted"]])
            ropts["resume_json"] = json.dumps(ckpt)
            return ropts, "resume", [], len(ckpt["emitted"])
        if not prompt_ids or not emitted:
            return None
        if self._resume_enabled(cfg):
            # ungraceful death: synthesize the token from bridge state —
            # no RNG key (sampled requests resample from a fresh key) and
            # no chain hashes (the pool died with the process; re-admission
            # degrades to re-prefill)
            tok = {"v": 1, "prompt_ids": prompt_ids, "emitted": emitted,
                   "sent_chars": sent_chars, "generated": len(emitted),
                   "chain": [], "key": None}
            ropts["prompt_ids"] = prompt_ids + emitted
            ropts["resume_json"] = json.dumps(tok)
            return ropts, "resume", [], len(emitted)
        if (float(opts.get("temperature") or 0.0) == 0.0
                and not opts.get("tools_json")
                and not opts.get("stop_prompts")):
            # deterministic replay (resume disabled): fold all but a short
            # verification tail into the prompt; the tail re-generates and
            # must match what the client already received
            tail = min(len(emitted), 4)
            keep = len(emitted) - tail
            ropts["prompt_ids"] = prompt_ids + emitted[:keep]
            ropts["tokens"] = max(1, orig_tokens - keep)
            return ropts, "replay", list(emitted[keep:]), keep
        return None

    async def _pump_stream(self, handle, opts: dict):
        """Bridge the blocking gRPC stream into an async queue."""
        loop = asyncio.get_running_loop()
        # Bounded queue + BLOCKING put from the pump thread: backpressure
        # propagates to the gRPC stream instead of dropping chunks (or the
        # terminal sentinel) when the HTTP client reads slower than the
        # backend decodes. `stopped` ends the pump when the client goes away
        # so an abandoned stream doesn't buffer the rest of the generation.
        q: asyncio.Queue = asyncio.Queue(maxsize=256)
        stopped = threading.Event()
        call = handle.client.predict_stream(**opts)

        def _put(item) -> bool:
            """Blocking put with backpressure; bounded waits so a stopped
            consumer (or a dead event loop) can never wedge the pump thread."""
            while not stopped.is_set():
                fut = asyncio.run_coroutine_threadsafe(q.put(item), loop)
                try:
                    fut.result(timeout=1.0)
                    return True
                except TimeoutError:
                    if not fut.cancel():
                        try:
                            fut.result(timeout=0)
                            return True
                        except Exception:
                            return False
                except Exception:
                    return False
            return False

        def pump():
            try:
                for reply in call:
                    if not _put(("chunk", reply)):
                        return
                _put(("done", None))
            except Exception as e:
                if not stopped.is_set():
                    _put(("error", e))

        loop.run_in_executor(None, pump)
        try:
            while True:
                kind, item = await q.get()
                if kind == "chunk":
                    yield item
                elif kind == "done":
                    return
                else:
                    raise item
        finally:
            stopped.set()
            # cancelling the RPC unblocks a pump waiting on the next reply
            # (client gone mid-generation) and tells the backend to stop
            call.cancel()
            while not q.empty():
                q.get_nowait()

    # ------------------------------------------------------------ endpoints

    async def _health(self, request):
        # kv_digest: served-prefix gossip for the federation picker's KV
        # affinity (ISSUE 17) — top-k most recent text-chain ids
        return web.json_response({
            "status": "ok",
            "kv_digest": self._kv_served.to_list(k=256),
        })

    def _note_served(self, body: dict):
        """Record a conversation's text-chain ids in the served-prefix
        digest (same helpers the federation proxy hashes the raw body
        with, so the ids agree by construction)."""
        from localai_tpu.engine.kvhost import (
            body_prompt_text, text_chain_ids,
        )

        try:
            self._kv_served.add(text_chain_ids(body_prompt_text(body)))
        except Exception:
            pass   # gossip is advisory — never fail the request for it

    async def _metrics(self, request):
        if not _HAVE_PROM:
            raise web.HTTPNotImplemented()
        await asyncio.to_thread(self._refresh_stage_gauges)
        return web.Response(body=generate_latest(),
                            content_type=CONTENT_TYPE_LATEST.split(";")[0])

    def _refresh_stage_gauges(self):
        """Pull each loaded backend's prof_* + hist_* metrics into the
        Prometheus series (best-effort — a wedged backend must not fail the
        scrape, and profile-less runs simply publish nothing)."""
        for (model, event), n in list(self.manager.events.items()):
            _counter_sync(_SUPERVISION, (model, event), float(n))
        for name in self.manager.loaded():
            h = self.manager.get(name)
            if h is None:
                continue
            try:
                m = h.client.metrics(timeout=2.0)
            except Exception:
                continue
            # SLO histograms: rebuilt whole from the flat keys; the custom
            # collector exposes them as true histogram series
            hists = telemetry.parse_flat(m)
            if hists:
                _SLO_SCRAPE[name] = hists
            for key, v in m.items():
                # scheduler X-ray series (ISSUE 13)
                if key.startswith("sched_reason__"):
                    _counter_sync(_SCHED_REASONS, (name, key[14:]), float(v))
                    continue
                if key.startswith("sched_variant__"):
                    _counter_sync(_SCHED_DISPATCHES, (name, key[15:]),
                                  float(v))
                    continue
                if key == "sched_ticks_total":
                    _counter_sync(_SCHED_TICKS, (name,), float(v))
                    continue
                if key == "sched_budget_utilization":
                    _SCHED_UTIL.labels(name).set(v)
                    continue
                if key == "sched_pad_rows_frac":
                    _SCHED_PAD.labels(name).set(v)
                    continue
                # host KV tier (ISSUE 17): occupancy levels vs cumulative
                # event counts out of the same kv_host_* key family
                if key in ("kv_host_blocks", "kv_host_bytes",
                           "kv_host_bytes_peak"):
                    _KV_HOST.labels(name, key[8:]).set(v)
                    continue
                if key in ("kv_host_hits", "kv_host_spills",
                           "kv_host_evictions"):
                    _counter_sync(_KV_HOST_EVENTS, (name, key[8:]),
                                  float(v))
                    continue
                if not key.startswith("prof_"):
                    continue
                stage, _, kind = key[5:].rpartition("_")
                if kind == "count":
                    _counter_sync(_STAGE_DISPATCHES, (name, stage), float(v))
                elif kind == "s" and stage.endswith("_tok"):
                    _STAGE_TOK_S.labels(name, stage[:-4]).set(v)
                elif kind == "ms" and stage.endswith("_total"):
                    _counter_sync(_STAGE_SECONDS, (name, stage[:-6]),
                                  v / 1e3)

    async def _backend_traces(self, model: str = "") -> list[dict]:
        """GetTrace payloads from the loaded backends ({} on any failure)."""
        out = []
        for name in self.manager.loaded():
            if model and name != model:
                continue
            h = self.manager.get(name)
            if h is None:
                continue
            try:
                payload = await asyncio.to_thread(
                    lambda hh=h: hh.client.trace())
            except Exception:
                payload = {}
            # key by the config name — the backend reports its checkpoint
            # path as model_name, which is not what clients query by
            payload["model"] = name
            out.append(payload)
        return out

    async def _debug_trace(self, request):
        """GET /debug/trace[?model=x] → Chrome-trace JSON merging this
        process's spans with every backend subprocess's (load it at
        chrome://tracing or ui.perfetto.dev). Empty traceEvents unless the
        server runs with LOCALAI_TRACE=1."""
        events = list(telemetry.chrome_events())
        names = {os.getpid(): "localai-http"}
        for payload in await self._backend_traces(
                request.query.get("model", "")):
            events.extend(payload.get("spans") or [])
            if payload.get("pid"):
                names[payload["pid"]] = f"backend:{payload['model']}"
        events.sort(key=lambda e: e.get("ts", 0))
        return web.json_response(telemetry.chrome_trace(events, names))

    async def _debug_profile(self, request):
        """GET /debug/profile[?model=x] → per-model device-step stage
        breakdown (histograms, tokens/s, MFU) from the engine profiler.
        Stages populate only under LOCALAI_PROFILE=1."""
        profiles = {}
        for payload in await self._backend_traces(
                request.query.get("model", "")):
            profiles[payload["model"]] = payload.get("profile") or {}
        return web.json_response({
            "tracing_enabled": telemetry.trace_enabled(),
            "profiling_enabled": telemetry.profile_enabled(),
            "models": profiles,
        })

    async def _debug_slo(self, request):
        """GET /debug/slo[?model=x] → per-model p50/p95/p99 snapshot of the
        serving SLO histograms (ttft/tpot/queue_wait/prefill/e2e, split by
        decode path), straight from each backend engine's registry. Empty
        per-model blocks when LOCALAI_METRICS=0."""
        models = {}
        kv_host = {}
        for payload in await self._backend_traces(
                request.query.get("model", "")):
            models[payload["model"]] = payload.get("slo") or {}
            if payload.get("kvhost"):
                # host KV tier occupancy/hit stats (ISSUE 17) — present
                # only for backends running with kv_host_bytes > 0
                kv_host[payload["model"]] = payload["kvhost"]
        return web.json_response({
            "metrics_enabled": telemetry.metrics_enabled(),
            "bucket_edges_s": [b for b in telemetry.BUCKETS_S
                               if b != float("inf")],
            "models": models,
            "kv_host": kv_host,
        })

    async def _debug_sched(self, request):
        """GET /debug/sched[?model=x] → the scheduler X-ray (ISSUE 13): each
        backend engine's tick-ledger snapshot — pack-composition totals,
        admission/fallback/demotion reason-code counters, per-variant
        dispatch counts and cost-analysis rooflines, plus the recent tick
        ring. Empty per-model blocks unless the backend runs with
        LOCALAI_SCHED=1 (and metrics enabled)."""
        models = {}
        for payload in await self._backend_traces(
                request.query.get("model", "")):
            models[payload["model"]] = payload.get("sched") or {}
        return web.json_response({
            "sched_enabled": telemetry.sched_enabled(),
            "reason_codes": {code: {"category": cat, "description": desc}
                             for code, (cat, desc)
                             in telemetry.REASON_CODES.items()},
            "models": models,
        })

    async def _debug_flightrec(self, request):
        """GET /debug/flightrec[?model=x] → the flight recorder rings: this
        process's events plus each backend's recent request timelines,
        engine-tick summaries, and tripwire/breaker/supervision events."""
        models = {}
        for payload in await self._backend_traces(
                request.query.get("model", "")):
            models[payload["model"]] = payload.get("flightrec") or {}
        return web.json_response({
            "server": telemetry.flightrec().dump(),
            "models": models,
        })

    async def _models(self, request):
        return web.json_response(schema.models_list(self.configs.names()))

    @staticmethod
    def _extract_images(messages):
        """OpenAI vision content parts → (flattened messages, images list).

        image_url parts become an <image> marker in the text (the LLaVA
        placeholder the backend expands, models/llava.py) and their payload
        joins the proto `images` list (reference: base64 images through
        PredictOptions.images, backend.proto:131; content-part handling in
        core/http/endpoints/openai chat)."""
        images, out = [], []
        for m in messages:
            c = m.get("content")
            if not isinstance(c, list):
                out.append(m)
                continue
            parts = []
            for part in c:
                t = part.get("type")
                if t in ("image_url", "input_image"):
                    url = part.get("image_url")
                    if isinstance(url, dict):
                        url = url.get("url", "")
                    url = url or part.get("url", "")
                    if url.startswith("http://") or url.startswith("https://"):
                        url = _fetch_image(url)
                    images.append(url)
                    parts.append("<image>")
                elif t in ("text", "input_text"):
                    parts.append(part.get("text", ""))
            out.append(dict(m, content="\n".join(p for p in parts if p)))
        return out, images

    async def _chat(self, request):
        body = await request.json()
        cfg = self._resolve(body)
        self._note_served(body)
        messages = body.get("messages") or []
        if not messages:
            raise web.HTTPBadRequest(
                text=json.dumps(schema.error_body("messages required")),
                content_type="application/json")
        try:
            messages, images = await asyncio.to_thread(
                self._extract_images, messages)
        except Exception as e:
            raise web.HTTPBadRequest(
                text=json.dumps(schema.error_body(f"bad image: {e}")),
                content_type="application/json")
        opts = self._merged_options(cfg, body)
        if images:
            opts["images"] = images
        if cfg.template.use_tokenizer_template or not cfg.template.chat:
            opts["messages_json"] = json.dumps(messages)
            opts["use_tokenizer_template"] = True
            if body.get("tools"):
                # the backend renders these into the prompt through the
                # tokenizer chat template's `tools` variable
                opts["tools_json"] = json.dumps(body["tools"])
        else:
            from localai_tpu.templates import evaluate_chat

            opts["prompt"] = evaluate_chat(cfg, messages)

        # response_format wins over tools in grammar_for_request — the output
        # is then the USER's structured format, never a tool call
        tools_active = (bool(body.get("tools"))
                        and body.get("tool_choice") != "none"
                        and not body.get("response_format"))
        async with self._admit(cfg):
            if body.get("stream"):
                return await self._chat_stream(request, cfg, opts,
                                               tools_active=tools_active,
                                               body=body)
            reply = await self._unary(cfg, "Predict", **opts)
            text = reply.message.decode("utf-8", "replace")
            tool_calls = None
            if tools_active:
                # grammar-constrained output → OpenAI tool_calls; the
                # no-action "answer" alternative unwraps back into prose
                # (reference: pkg/functions/parse.go + functions.go no-action,
                # wired at chat.go:266-312)
                from localai_tpu.functions import parse_tool_response

                tool_calls, answer = parse_tool_response(text)
                if answer is not None:
                    text = answer
            timings = {
                "prompt_processing_s": reply.timing_prompt_processing,
                "token_generation_s": reply.timing_token_generation,
            }
            timings.update(_engine_timings(reply))
            resp = schema.chat_completion(
                cfg.name, text,
                reply.finish_reason, reply.prompt_tokens, reply.tokens,
                timings=timings,
                tool_calls=tool_calls)
            schema.merge_extra_usage(
                resp, bool(request.headers.get("Extra-Usage")),
                reply.timing_prompt_processing,
                reply.timing_token_generation)
            return web.json_response(resp)

    async def _sse_error(self, resp, send, e: Exception):
        """Mid-stream failure → a clean terminal SSE error event + [DONE]
        (never a silently hung or truncated connection — ISSUE 4). Best
        effort: the client itself may already be gone."""
        status = getattr(e, "status", 500)
        kind = {429: "overloaded_error", 503: "server_error",
                504: "timeout_error"}.get(status, "server_error")
        try:
            await send(schema.error_body(f"{e}", kind, status))
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
        except (ConnectionError, RuntimeError):
            pass
        return resp

    async def _chat_stream(self, request, cfg, opts,
                           tools_active: bool = False, body: dict | None = None):
        """SSE loop (reference chat.go:334-449): role chunk, deltas, usage
        chunk, data: [DONE]. With tools active the output is buffered (it is
        a grammar-constrained JSON object, meaningless as partial text) and
        emitted as one tool_calls delta, finish_reason "tool_calls"."""
        # load failures before any SSE bytes surface as plain HTTP errors
        await self._handle(cfg)
        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "Connection": "keep-alive",
        })
        await resp.prepare(request)
        rid = schema._id("chatcmpl")

        async def send(obj):
            await resp.write(f"data: {json.dumps(obj)}\n\n".encode())

        await send(schema.chat_chunk(rid, cfg.name, None, role=True))
        prompt_tokens = completion_tokens = 0
        t_prompt = t_gen = 0.0
        finish = "stop"
        buffered: list[str] = []
        timings: dict = {}
        try:
            async for reply in self._stream_rpc(cfg, opts):
                prompt_tokens = reply.prompt_tokens
                completion_tokens = reply.tokens
                t_prompt = reply.timing_prompt_processing or t_prompt
                t_gen = reply.timing_token_generation or t_gen
                timings = _engine_timings(reply) or timings
                text = reply.message.decode("utf-8", "replace")
                if text:
                    if tools_active:
                        buffered.append(text)
                    else:
                        await send(schema.chat_chunk(rid, cfg.name, text))
                if reply.finish_reason:
                    finish = reply.finish_reason
        except (asyncio.CancelledError, ConnectionError):
            raise          # client went away — nothing left to tell it
        except Exception as e:
            return await self._sse_error(resp, send, e)
        if tools_active:
            from localai_tpu.functions import parse_tool_response

            full = "".join(buffered)
            calls, answer = parse_tool_response(full)
            if calls:
                await send(schema.chat_chunk(rid, cfg.name, None,
                                             tool_calls=calls))
                finish = "tool_calls"
            elif answer is not None:
                # the no-action "answer" alternative: emit its message as a
                # plain content delta (prose, not a forced tool call)
                if answer:
                    await send(schema.chat_chunk(rid, cfg.name, answer))
            elif full:
                await send(schema.chat_chunk(rid, cfg.name, full))
        await send(schema.chat_chunk(rid, cfg.name, None, finish_reason=finish))
        stream_opts = (body or {}).get("stream_options") or {}
        if stream_opts.get("include_usage", True):
            # default-on: LocalAI clients expect the usage tail unless the
            # OpenAI stream_options flag explicitly disables it
            tail = schema.chat_usage_chunk(rid, cfg.name, prompt_tokens,
                                           completion_tokens)
            schema.merge_extra_usage(
                tail, bool(request.headers.get("Extra-Usage")),
                t_prompt, t_gen)
            if timings:
                # llama.cpp-style per-request timings in the final chunk
                tail["timings"] = timings
            await send(tail)
        await resp.write(b"data: [DONE]\n\n")
        await resp.write_eof()
        return resp

    async def _completions(self, request):
        body = await request.json()
        cfg = self._resolve(body)
        self._note_served(body)
        prompt = body.get("prompt") or ""
        if isinstance(prompt, list):
            prompt = prompt[0] if prompt else ""
        opts = self._merged_options(cfg, body)
        if cfg.template.completion:
            from localai_tpu.templates import evaluate_completion

            prompt = evaluate_completion(cfg, prompt)
        opts["prompt"] = prompt

        async with self._admit(cfg):
            if body.get("stream"):
                return await self._completion_stream(request, cfg, opts)
            reply = await self._unary(cfg, "Predict", **opts)
            out = schema.text_completion(
                cfg.name, reply.message.decode("utf-8", "replace"),
                reply.finish_reason, reply.prompt_tokens, reply.tokens)
            schema.merge_extra_usage(
                out, bool(request.headers.get("Extra-Usage")),
                reply.timing_prompt_processing,
                reply.timing_token_generation)
            return web.json_response(out)

    async def _completion_stream(self, request, cfg, opts):
        await self._handle(cfg)   # load errors stay plain HTTP, not SSE
        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
        })
        await resp.prepare(request)
        rid = schema._id("cmpl")
        finish = "stop"
        prompt_tokens = completion_tokens = 0
        t_prompt = t_gen = 0.0
        timings: dict = {}

        async def send(obj):
            await resp.write(f"data: {json.dumps(obj)}\n\n".encode())

        try:
            async for reply in self._stream_rpc(cfg, opts):
                text = reply.message.decode("utf-8", "replace")
                prompt_tokens = reply.prompt_tokens
                completion_tokens = reply.tokens
                t_prompt = reply.timing_prompt_processing or t_prompt
                t_gen = reply.timing_token_generation or t_gen
                timings = _engine_timings(reply) or timings
                if reply.finish_reason:
                    finish = reply.finish_reason
                if text:
                    await send(schema.text_completion_chunk(rid, cfg.name,
                                                            text))
        except (asyncio.CancelledError, ConnectionError):
            raise
        except Exception as e:
            return await self._sse_error(resp, send, e)
        final = schema.text_completion_chunk(rid, cfg.name, "", finish)
        if timings:
            final["timings"] = timings
        if request.headers.get("Extra-Usage"):
            # reference completion.go:74 parity on the stream too
            final["usage"] = schema.usage(prompt_tokens, completion_tokens)
            schema.merge_extra_usage(final, True, t_prompt, t_gen)
        await resp.write(
            f"data: {json.dumps(final)}\n\n".encode())
        await resp.write(b"data: [DONE]\n\n")
        await resp.write_eof()
        return resp

    async def _embeddings(self, request):
        body = await request.json()
        cfg = self._resolve(body)
        inputs = body.get("input") or ""
        if isinstance(inputs, str):
            inputs = [inputs]
        async with self._admit(cfg):
            # ONE RPC for the whole batch → one bucketed device call
            # (a batch-256 request used to make 512 round trips)
            r = await self._unary(cfg, "Embedding", prompts=inputs)
            vectors = [list(v.values) for v in r.vectors]
            return web.json_response(schema.embeddings_response(
                cfg.name, vectors, r.prompt_tokens))

    async def _rerank(self, request):
        body = await request.json()
        cfg = self._resolve(body)
        async with self._admit(cfg):
            r = await self._unary(cfg, "Rerank",
                                  query=body.get("query", ""),
                                  documents=body.get("documents", []),
                                  top_n=body.get("top_n", 0))
            return web.json_response({
                "model": cfg.name,
                "results": [{
                    "index": d.index,
                    "relevance_score": d.relevance_score,
                    "document": {"text": d.text},
                } for d in r.results],
            })

    async def _edits(self, request):
        """POST /v1/edits — legacy OpenAI edit API (reference
        endpoints/openai/edit.go, routed at routes/openai.go:56): apply
        `instruction` to `input` via the completion path."""
        body = await request.json()
        cfg = self._resolve(body)
        instruction = body.get("instruction", "")
        if not instruction:
            raise web.HTTPBadRequest(text="instruction required")
        inp = body.get("input", "")
        prompt = (f"Text: {inp}\nInstruction: {instruction}\n"
                  f"Edited text:")
        sub = {"model": cfg.name, "prompt": prompt}
        for f in _SAMPLING_FIELDS + ("max_tokens",):
            if f in body:
                sub[f] = body[f]
        # forward the Extra-Usage opt-in (reference edit.go:35) — the
        # completion leg then merges timings into the usage we relay
        eu = request.headers.get("Extra-Usage")
        resp = await self._loopback(
            "/v1/completions", sub,
            extra_headers={"Extra-Usage": eu} if eu else None)
        return web.json_response({
            "object": "edit",
            "created": int(time.time()),
            "choices": [{"index": i, "text": c.get("text", "")}
                        for i, c in enumerate(resp.get("choices", []))],
            "usage": resp.get("usage", {}),
        })

    async def _loopback(self, path: str, body: dict,
                        extra_headers: dict | None = None) -> dict:
        """POST to our own API (the reference's MCP agent does the same —
        mcp.go hands the local API address to the agent loop)."""
        import aiohttp

        headers = dict(extra_headers or {})
        if self.cfg.api_keys:
            headers["Authorization"] = f"Bearer {self.cfg.api_keys[0]}"
        url = f"http://{self.cfg.address}{path}"
        async with aiohttp.ClientSession() as s:
            async with s.post(url, json=body, headers=headers,
                              timeout=aiohttp.ClientTimeout(total=600)) as r:
                if r.status != 200:
                    raise web.HTTPInternalServerError(
                        text=f"loopback {path} failed: {await r.text()}")
                return await r.json()

    def _mcp_sessions_for(self, cfg):
        from localai_tpu.mcp import sessions_from_config

        with self._mcp_lock:
            cached = self._mcp_sessions.get(cfg.name)
        if cached is not None:
            return cached
        # session setup (process spawn + initialize handshake) happens
        # OUTSIDE the lock: a wedged server must not block other models
        sessions = sessions_from_config(cfg.mcp)
        with self._mcp_lock:
            existing = self._mcp_sessions.get(cfg.name)
            if existing is None:
                self._mcp_sessions[cfg.name] = sessions
                return sessions
        # lost the race: keep the first set, and close OUR spawned
        # sessions outside the lock — close() terminates the server
        # process and waits on it (lockdep flagged the old in-lock close:
        # a wedged MCP server would have blocked every model's MCP path)
        for s in sessions:
            try:
                s.close()
            except Exception:
                pass
        return existing

    def _mcp_evict(self, name: str):
        """Drop (and close) a model's cached MCP sessions — called when a
        transport dies so the next request reconnects instead of failing
        forever."""
        with self._mcp_lock:
            sessions = self._mcp_sessions.pop(name, None)
        for s in sessions or []:
            try:
                s.close()
            except Exception:
                pass

    async def _mcp_chat(self, request):
        """POST /mcp/v1/chat/completions — agentic chat with the model
        config's MCP servers' tools (reference mcp.go:1-142): the model's
        tool_calls are executed against the MCP sessions and fed back until
        it answers in prose (or the iteration budget runs out)."""
        body = await request.json()
        cfg = self._resolve(body)
        if not cfg.mcp:
            raise web.HTTPBadRequest(
                text=f"model {cfg.name!r} has no MCP servers configured")
        from localai_tpu.mcp import tools_as_openai

        try:
            sessions = await asyncio.to_thread(self._mcp_sessions_for, cfg)
        except Exception as e:
            raise web.HTTPInternalServerError(
                text=f"MCP session setup failed: {e}")
        tools, owner = tools_as_openai(sessions)
        if not tools:
            raise web.HTTPInternalServerError(
                text="no tools offered by the configured MCP servers")

        messages = list(body.get("messages") or [])
        if not messages and body.get("prompt"):
            messages = [{"role": "user", "content": body["prompt"]}]
        max_iter = int((cfg.agent or {}).get("max_iterations", 3))
        last = {}
        for it in range(max_iter):
            sub = {"model": cfg.name, "messages": messages}
            for f in _SAMPLING_FIELDS + ("max_tokens",):
                if f in body:
                    sub[f] = body[f]
            if it < max_iter - 1:
                sub["tools"] = tools   # final round: force a prose answer
                # the agent loop's contract is call-then-answer: non-final
                # rounds must produce a tool call (tool_choice "required"
                # keeps the no-action "answer" alternative out of the
                # grammar here — the final tool-less round is the answer)
                sub["tool_choice"] = "required"
                # a truncated tool-call JSON cannot parse — give the
                # grammar-constrained round enough budget to close the braces
                sub["max_tokens"] = max(int(sub.get("max_tokens") or 0), 128)
            last = await self._loopback("/v1/chat/completions", sub)
            choice = (last.get("choices") or [{}])[0]
            msg = choice.get("message", {})
            calls = msg.get("tool_calls")
            if not calls:
                break
            # the chat template renders only role+content, so serialize the
            # calls INTO the content — the next round's prompt must show
            # which tool was called with what and which result is whose
            call_desc = "; ".join(
                f"{c.get('function', {}).get('name', '?')}"
                f"({c.get('function', {}).get('arguments', '')})"
                for c in calls)
            messages.append({"role": "assistant", "tool_calls": calls,
                             "content": f"[tool calls] {call_desc}"})
            from localai_tpu.mcp import MCPError

            for call in calls:
                fn = call.get("function", {})
                name = fn.get("name", "")
                try:
                    args = json.loads(fn.get("arguments") or "{}")
                except ValueError:
                    args = {}
                sess = owner.get(name)
                if sess is None:
                    result = f"error: unknown tool {name!r}"
                else:
                    try:
                        result = await asyncio.to_thread(
                            sess.call_tool, name, args)
                    except MCPError as e:
                        # transport died: evict so the NEXT request
                        # reconnects instead of failing forever
                        self._mcp_evict(cfg.name)
                        result = f"error: {e}"
                    except Exception as e:
                        result = f"error: {e}"
                messages.append({"role": "tool",
                                 "tool_call_id": call.get("id", name),
                                 "name": name,
                                 "content": f"[{name}] {result}"})
        return web.json_response(last)

    async def _detection(self, request):
        """POST /v1/detection {model, image: base64|data-URI|file path} →
        {detections: [{x, y, width, height, confidence, class_name}]}
        (reference endpoints/localai/detection.go + schema.DetectionRequest)."""
        import base64
        import os
        import tempfile

        body = await request.json()
        cfg = self._resolve(body)
        image = body.get("image", "")
        if not image:
            raise web.HTTPBadRequest(text="image required")
        tmp = None
        if os.path.isfile(image):
            src = image
        else:
            if image.startswith("data:"):
                image = image.split(",", 1)[-1]
            try:
                blob = base64.b64decode(image, validate=True)
            except Exception:
                raise web.HTTPBadRequest(
                    text="image must be a file path, base64, or data URI")
            tmp = tempfile.NamedTemporaryFile(suffix=".img", delete=False)
            tmp.write(blob)
            tmp.close()
            src = tmp.name
        try:
            handle = await self._handle(cfg)
            handle.mark_busy()
            try:
                r = await asyncio.to_thread(
                    lambda: handle.client.detect(src=src))
                return web.json_response({"detections": [{
                    "x": d.x, "y": d.y, "width": d.width, "height": d.height,
                    "confidence": d.confidence, "class_name": d.class_name,
                } for d in r.detections]})
            finally:
                handle.mark_idle()
        finally:
            if tmp is not None:
                os.unlink(tmp.name)

    async def _tokenize(self, request):
        body = await request.json()
        cfg = self._resolve(body)
        handle = await self._handle(cfg)
        handle.mark_busy()
        try:
            t = await asyncio.to_thread(
                lambda: handle.client.tokenize(body.get("content", "")))
        finally:
            handle.mark_idle()
        return web.json_response({"tokens": list(t.tokens)})

    async def _backend_monitor(self, request):
        out = {}
        for name in self.manager.loaded():
            h = self.manager.get(name)
            if h is None:
                continue
            st = await asyncio.to_thread(lambda hh=h: hh.client.status())
            try:
                metrics = await asyncio.to_thread(
                    lambda hh=h: hh.client.metrics())
            except Exception:
                metrics = {}
            out[name] = {
                "state": int(st.state),
                "memory_total": st.memory.total,
                "busy": h.busy,
                # per-backend engine metrics (reference GetMetrics +
                # get_token_metrics.go role): tok/s, ttft, cache hits...
                "metrics": metrics,
            }
        return web.json_response(out)

    async def _backend_shutdown(self, request):
        """POST /backend/shutdown — graceful (ISSUE 4). With {"model": x}:
        drain that backend's in-flight requests (up to drain_timeout) then
        reap it. Without a model: server-wide drain — new work 503s while
        in-flight requests finish under the hard deadline, then every
        backend stops."""
        try:
            body = await request.json()
        except Exception:
            body = {}
        timeout = float(body.get("timeout",
                                 getattr(self.cfg, "drain_timeout", 30.0)))
        model = body.get("model", "")
        if model:
            ok = await asyncio.to_thread(
                self.manager.drain_model, model, timeout)
            return web.json_response({"success": ok})
        await self._drain(timeout)
        return web.json_response({"success": True, "draining": True})

    async def _backend_preempt(self, request):
        """POST /backend/preempt {"model": x, "grace": s} — preemption
        notice (ISSUE 19): SIGTERM the model's backend so live slots freeze
        into ResumeTokens (spill-drain) instead of finishing; their streams
        resume transparently on the respawned backend. Unlike
        /backend/shutdown this checkpoints requests mid-flight rather than
        waiting for them."""
        try:
            body = await request.json()
        except Exception:
            body = {}
        model = body.get("model", "")
        if not model:
            return web.json_response(
                schema.error_body("model required", code=400), status=400)
        grace = body.get("grace")
        ok = await asyncio.to_thread(
            self.manager.preempt_model, model,
            float(grace) if grace is not None else None)
        return web.json_response({"success": ok})

    async def _drain(self, timeout: float):
        """Reject new work (middleware 503s while self._draining), wait for
        in-flight requests to finish — hard deadline — then stop backends."""
        self._draining = True
        loop = asyncio.get_running_loop()
        deadline = loop.time() + max(timeout, 0.0)
        while self._inflight > 0 and loop.time() < deadline:
            await asyncio.sleep(0.05)
        await asyncio.to_thread(self.manager.stop_all)

    async def _on_shutdown(self, app):
        # SIGTERM/cleanup path: drain unless an explicit /backend/shutdown
        # already did
        if not self._draining:
            await self._drain(getattr(self.cfg, "drain_timeout", 30.0))

    async def _realtime(self, request):
        from localai_tpu.server.realtime import realtime_handler

        return await realtime_handler(self, request)

    async def _realtime_session(self, request):
        from localai_tpu.server.realtime import session_factory_handler

        return await session_factory_handler(self, request, "conversation")

    async def _realtime_transcription_session(self, request):
        from localai_tpu.server.realtime import session_factory_handler

        return await session_factory_handler(self, request, "transcription")

    # ------------------------------------------------------ image endpoints
    # (reference: endpoints/openai/image.go — b64_json/url response shapes)

    def _media_cfg(self, body: dict, backend: str) -> ModelConfig:
        name = body.get("model") or f"default-{backend}"
        cfg = self.configs.get(name)
        if cfg is None:
            cfg = ModelConfig(name=name, backend=backend)
        return cfg

    async def _images(self, request):
        import base64
        import tempfile

        body = await request.json()
        cfg = self._media_cfg(body, "image")
        handle = await self._handle(cfg)
        size = (body.get("size") or "256x256").lower().split("x")
        w, h = int(size[0]), int(size[1] if len(size) > 1 else size[0])
        with tempfile.NamedTemporaryFile(suffix=".png", delete=False) as t:
            path = t.name
        handle.mark_busy()
        try:
            await asyncio.to_thread(lambda: handle.client.generate_image(
                positive_prompt=body.get("prompt", ""),
                negative_prompt=body.get("negative_prompt", ""),
                width=w, height=h,
                step=int(body.get("step", 0)),
                seed=int(body.get("seed", 0)),
                dst=path))
            with open(path, "rb") as f:
                data = f.read()
            return web.json_response({"created": int(time.time()), "data": [
                {"b64_json": base64.b64encode(data).decode()}]})
        finally:
            handle.mark_idle()
            import os as _os

            _os.unlink(path)

    async def _videos(self, request):
        import base64
        import tempfile

        body = await request.json()
        cfg = self._media_cfg(body, "image")
        handle = await self._handle(cfg)
        with tempfile.NamedTemporaryFile(suffix=".gif", delete=False) as t:
            path = t.name
        handle.mark_busy()
        try:
            await asyncio.to_thread(
                lambda: handle.client.generate_video(
                    prompt=body.get("prompt", ""),
                    num_frames=int(body.get("num_frames", 8)),
                    fps=int(body.get("fps", 4)),
                    seed=int(body.get("seed", 0)),
                    dst=path))
            with open(path, "rb") as f:
                data = f.read()
            return web.json_response({"created": int(time.time()), "data": [
                {"b64_json": base64.b64encode(data).decode(),
                 "mime_type": "image/gif"}]})
        finally:
            handle.mark_idle()
            import os as _os

            _os.unlink(path)

    # ------------------------------------------------------ audio endpoints
    # (reference: endpoints/openai/transcription.go + localai tts/vad routes)

    async def _transcriptions(self, request):
        """OpenAI /v1/audio/transcriptions: multipart form (file, model)."""
        import tempfile

        form = await request.post()
        upload = form.get("file")
        if upload is None:
            raise web.HTTPBadRequest(
                text=json.dumps(schema.error_body("file field required")),
                content_type="application/json")
        cfg = self._resolve({"model": form.get("model", "")})
        handle = await self._handle(cfg)
        with tempfile.NamedTemporaryFile(suffix=".wav", delete=False) as t:
            t.write(upload.file.read())
            path = t.name
        handle.mark_busy()
        try:
            r = await asyncio.to_thread(
                lambda: handle.client.transcribe(
                    dst=path, language=form.get("language", "")))
            return web.json_response({
                "text": r.text,
                "segments": [{
                    "id": s.id, "start": s.start / 1e9, "end": s.end / 1e9,
                    "text": s.text,
                } for s in r.segments],
            })
        finally:
            handle.mark_idle()
            import os as _os

            _os.unlink(path)

    async def _tts_wav(self, name: str, text: str, voice: str,
                       language: str) -> web.Response:
        """Shared one-shot TTS → WAV response (speech/tts/elevenlabs routes)."""
        import os as _os
        import tempfile

        cfg = self.configs.get(name)
        if cfg is None:
            cfg = ModelConfig(name=name, backend="tts")
        handle = await self._handle(cfg)
        with tempfile.NamedTemporaryFile(suffix=".wav", delete=False) as t:
            path = t.name
        handle.mark_busy()
        try:
            r = await asyncio.to_thread(lambda: handle.client.tts(
                text=text, voice=voice, dst=path, language=language))
            if not r.success:
                raise web.HTTPInternalServerError(
                    text=json.dumps(schema.error_body(
                        f"tts failed: {r.message}", "server_error", 500)),
                    content_type="application/json")
            with open(path, "rb") as f:
                data = f.read()
            return web.Response(body=data, content_type="audio/wav")
        finally:
            handle.mark_idle()
            _os.unlink(path)

    async def _speech(self, request):
        """OpenAI /v1/audio/speech + localai /tts → WAV bytes."""
        body = await request.json()
        return await self._tts_wav(
            body.get("model") or "default-tts",
            body.get("input") or body.get("text") or "",
            body.get("voice", ""), body.get("language", ""))

    async def _webui(self, request):
        from localai_tpu.server.webui import INDEX_HTML

        return web.Response(text=INDEX_HTML, content_type="text/html")

    async def _elevenlabs_tts(self, request):
        """elevenlabs-shaped TTS: voice from the path, text in the body
        (reference core/http/endpoints/elevenlabs/tts.go)."""
        body = await request.json()
        return await self._tts_wav(
            body.get("model_id") or body.get("model") or "default-tts",
            body.get("text") or "",
            request.match_info.get("voice_id", ""),
            body.get("language_code", ""))

    async def _vad(self, request):
        body = await request.json()
        name = body.get("model") or "default-tts"
        cfg = self.configs.get(name)
        if cfg is None:
            cfg = ModelConfig(name=name, backend="tts")
        handle = await self._handle(cfg)
        handle.mark_busy()
        try:
            r = await asyncio.to_thread(
                lambda: handle.client.vad(body.get("audio", [])))
        finally:
            handle.mark_idle()
        return web.json_response({"segments": [
            {"start": s.start, "end": s.end} for s in r.segments]})

    async def _sound_generation(self, request):
        import tempfile

        body = await request.json()
        name = body.get("model") or "default-tts"
        cfg = self.configs.get(name)
        if cfg is None:
            cfg = ModelConfig(name=name, backend="tts")
        handle = await self._handle(cfg)
        with tempfile.NamedTemporaryFile(suffix=".wav", delete=False) as t:
            path = t.name
        handle.mark_busy()
        try:
            await asyncio.to_thread(
                lambda: handle.client.sound_generation(
                    text=body.get("text", body.get("input", "")),
                    duration=float(body.get("duration_seconds", 2.0)),
                    dst=path))
            with open(path, "rb") as f:
                data = f.read()
            return web.Response(body=data, content_type="audio/wav")
        finally:
            handle.mark_idle()
            import os as _os

            _os.unlink(path)

    # ------------------------------------------------------ stores endpoints
    # (reference: localai routes + backend/go/local-store; values are strings
    # on the wire, bytes at the backend)

    async def _store_handle(self, body: dict):
        name = body.get("store") or "default-store"
        cfg = self.configs.get(name)
        if cfg is None:
            cfg = ModelConfig(name=name, backend="store")
        return await self._handle(cfg)

    async def _stores_set(self, request):
        body = await request.json()
        h = await self._store_handle(body)
        h.mark_busy()
        try:
            await asyncio.to_thread(lambda: h.client.stores_set(
                body.get("keys", []),
                [v.encode() for v in body.get("values", [])]))
        finally:
            h.mark_idle()
        return web.json_response({})

    async def _stores_get(self, request):
        body = await request.json()
        h = await self._store_handle(body)
        h.mark_busy()
        try:
            r = await asyncio.to_thread(
                lambda: h.client.stores_get(body.get("keys", [])))
        finally:
            h.mark_idle()
        return web.json_response({
            "keys": [list(k.floats) for k in r.keys],
            "values": [v.bytes.decode("utf-8", "replace") for v in r.values],
        })

    async def _stores_delete(self, request):
        body = await request.json()
        h = await self._store_handle(body)
        h.mark_busy()
        try:
            await asyncio.to_thread(
                lambda: h.client.stores_delete(body.get("keys", [])))
        finally:
            h.mark_idle()
        return web.json_response({})

    async def _stores_find(self, request):
        body = await request.json()
        h = await self._store_handle(body)
        h.mark_busy()
        try:
            r = await asyncio.to_thread(lambda: h.client.stores_find(
                body.get("key", []), int(body.get("topk", 10))))
        finally:
            h.mark_idle()
        return web.json_response({
            "keys": [list(k.floats) for k in r.keys],
            "values": [v.bytes.decode("utf-8", "replace") for v in r.values],
            "similarities": list(r.similarities),
        })

    async def _system(self, request):
        from localai_tpu.system import system_info

        info = await asyncio.to_thread(system_info)
        info["loaded_models"] = self.manager.loaded()
        return web.json_response(info)

    # ------------------------------------------------------ gallery endpoints
    # (reference routes: /models/apply + job status, localai.go)

    def _require_gallery(self):
        if self.gallery_service is None:
            raise web.HTTPNotImplemented(
                text=json.dumps(schema.error_body(
                    "no galleries configured", code=501)),
                content_type="application/json")
        return self.gallery_service

    async def _models_apply(self, request):
        svc = self._require_gallery()
        body = await request.json()
        name = body.get("id") or body.get("model") or ""
        job = svc.submit(name, overrides=body.get("config_overrides"))
        return web.json_response({"uuid": job,
                                  "status": f"/models/jobs/{job}"})

    async def _models_available(self, request):
        svc = self._require_gallery()
        models = await asyncio.to_thread(svc.gallery.models)
        return web.json_response([{
            "name": m.name, "description": m.description, "tags": m.tags,
            "installed": self.configs.get(m.name) is not None,
        } for m in models.values()])

    async def _models_job(self, request):
        svc = self._require_gallery()
        st = svc.status.get(request.match_info["job_id"])
        if st is None:
            raise web.HTTPNotFound()
        if st.get("state") == "done":
            self.configs.reload()  # new YAML becomes servable immediately
        return web.json_response(st)

    # ------------------------------------------------ backend gallery

    async def _backends_list(self, request):
        from localai_tpu.services.backend_gallery import list_system_backends

        return web.json_response(await asyncio.to_thread(
            list_system_backends, self.cfg.backends_path))

    def _require_backend_gallery(self):
        if self.backend_gallery_service is None:
            raise web.HTTPBadRequest(
                text="no backend galleries configured "
                     "(--backend-galleries / LOCALAI_BACKEND_GALLERIES)")
        return self.backend_gallery_service

    async def _backends_available(self, request):
        from localai_tpu.services.backend_gallery import list_system_backends

        svc = self._require_backend_gallery()
        backends = await asyncio.to_thread(svc.gallery.backends)
        installed = {b["name"] for b in await asyncio.to_thread(
            list_system_backends, self.cfg.backends_path)}
        return web.json_response([{
            "name": b.name, "description": b.description, "tags": b.tags,
            "meta": b.is_meta, "installed": b.name in installed,
        } for b in backends.values()])

    async def _backends_galleries(self, request):
        svc = self._require_backend_gallery()
        return web.json_response([{"url": s} for s in svc.gallery.sources])

    async def _backends_apply(self, request):
        svc = self._require_backend_gallery()
        body = await request.json()
        name = body.get("id") or body.get("name") or ""
        if not name:
            raise web.HTTPBadRequest(text="backend name required")
        job = svc.submit(name)
        return web.json_response({"uuid": job,
                                  "status": f"/backends/jobs/{job}"})

    async def _backends_delete(self, request):
        from localai_tpu.services.backend_gallery import delete_backend

        try:
            await asyncio.to_thread(delete_backend,
                                    self.cfg.backends_path,
                                    request.match_info["name"])
        except KeyError as e:
            raise web.HTTPNotFound(text=str(e))
        return web.json_response({"deleted": True})

    async def _backends_job(self, request):
        svc = self._require_backend_gallery()
        st = svc.status.get(request.match_info["job_id"])
        if st is None:
            raise web.HTTPNotFound()
        return web.json_response(st)


def run_server(args) -> int:
    """CLI `run` entrypoint: assemble config + manager + API and serve
    (reference: core/application/startup.go + cmd/local-ai/main.go)."""
    from localai_tpu.core.startup import (
        ConfigWatcher, load_env_files, preload_models,
    )

    env_file = getattr(args, "env_file", None)
    load_env_files([env_file] if env_file else None)
    # --trace/--profile go through the environment so the ModelManager's
    # backend subprocesses (which inherit os.environ) pick them up too
    if getattr(args, "trace", False):
        os.environ["LOCALAI_TRACE"] = "1"
    if getattr(args, "profile", False):
        os.environ["LOCALAI_PROFILE"] = "1"
    app_cfg = AppConfig.from_env(
        address=getattr(args, "address", None),
        models_path=getattr(args, "models_path", None),
        context_size=getattr(args, "context_size", None),
        parallel_requests=getattr(args, "parallel_requests", None),
        tensor_parallel=getattr(args, "tensor_parallel", None),
        single_active_backend=getattr(args, "single_active_backend", None),
        api_keys=getattr(args, "api_keys", None),
        request_timeout=getattr(args, "request_timeout", None),
        retry_budget=getattr(args, "retry_budget", None),
        breaker_threshold=getattr(args, "breaker_threshold", None),
        breaker_cooldown=getattr(args, "breaker_cooldown", None),
        queue_depth=getattr(args, "queue_depth", None),
        drain_timeout=getattr(args, "drain_timeout", None),
        preempt_grace=getattr(args, "preempt_grace", None),
        kv_window=getattr(args, "kv_window", None),
        kv_sinks=getattr(args, "kv_sinks", None),
        kv_host_bytes=getattr(args, "kv_host_bytes", None),
    )
    for t in ("watchdog_idle_timeout", "watchdog_busy_timeout"):
        v = getattr(args, t, None)
        if v:
            setattr(app_cfg, t, float(v))
    configs = ModelConfigLoader(app_cfg.models_path)
    manager = ModelManager(app_cfg)
    manager.start_watchdog()
    api = API(app_cfg, configs, manager)
    galleries = getattr(args, "galleries", None)
    if galleries:
        from localai_tpu.services import Gallery, GalleryService

        svc = GalleryService(
            Gallery([s.strip() for s in galleries.split(",") if s.strip()]),
            app_cfg.models_path)
        svc.start()
        api.gallery_service = svc

    backends_path = getattr(args, "backends_path", None)
    if backends_path:
        app_cfg.backends_path = backends_path
    bgalleries = (getattr(args, "backend_galleries", None)
                  or os.environ.get("LOCALAI_BACKEND_GALLERIES", ""))
    if bgalleries:
        from localai_tpu.services.backend_gallery import (
            BackendGallery, BackendGalleryService,
        )

        app_cfg.backend_galleries = [
            s.strip() for s in bgalleries.split(",") if s.strip()]
        bsvc = BackendGalleryService(
            BackendGallery(app_cfg.backend_galleries),
            app_cfg.backends_path or os.path.join(
                app_cfg.models_path, "..", "backends"))
        if not app_cfg.backends_path:
            app_cfg.backends_path = bsvc.backends_path
        bsvc.start()
        api.backend_gallery_service = bsvc

    preload = getattr(args, "models", None) or []
    if preload:
        # warm the listed backends in the background so serving starts now
        # but first requests don't pay the model load (startup.go:65-105)
        threading.Thread(
            target=preload_models,
            args=(list(preload), configs, manager),
            kwargs={"gallery_service": getattr(api, "gallery_service", None)},
            daemon=True, name="preload").start()

    watcher = None
    if not getattr(args, "disable_config_watcher", False):
        watcher = ConfigWatcher(configs).start()

    host, _, port = app_cfg.address.rpartition(":")
    try:
        web.run_app(api.app, host=host or "127.0.0.1", port=int(port),
                    print=lambda *a: print(f"serving on {app_cfg.address}",
                                           flush=True))
    finally:
        if watcher:
            watcher.stop()
        manager.stop_all()
    return 0
