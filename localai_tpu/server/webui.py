"""Minimal built-in WebUI — the reference's chat UI role
(/root/reference/core/http/routes/ui.go + views/chat.html), rebuilt as one
dependency-free page: model picker from /v1/models, streaming chat over the
/v1/chat/completions SSE surface, and a status strip from /backend/monitor.
"""

INDEX_HTML = """<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<meta name="viewport" content="width=device-width, initial-scale=1">
<title>LocalAI-TPU</title>
<style>
  :root { --bg:#0f1117; --panel:#181b24; --line:#2a2f3d; --text:#e6e8ee;
          --dim:#9aa1b2; --accent:#7aa2f7; --user:#1f2636; }
  * { box-sizing: border-box; }
  body { margin:0; background:var(--bg); color:var(--text);
         font:15px/1.5 system-ui, sans-serif; display:flex;
         flex-direction:column; height:100vh; }
  header { display:flex; gap:12px; align-items:center; padding:10px 16px;
           background:var(--panel); border-bottom:1px solid var(--line); }
  header h1 { font-size:15px; margin:0; font-weight:600; }
  header h1 span { color:var(--accent); }
  select, button, textarea {
    background:var(--bg); color:var(--text); border:1px solid var(--line);
    border-radius:8px; font:inherit; }
  select { padding:6px 8px; }
  #status { margin-left:auto; color:var(--dim); font-size:12px; }
  #log { flex:1; overflow-y:auto; padding:16px; max-width:860px; width:100%;
         margin:0 auto; }
  .msg { padding:10px 14px; border-radius:10px; margin:8px 0;
         white-space:pre-wrap; word-break:break-word; }
  .user { background:var(--user); margin-left:15%; }
  .assistant { background:var(--panel); margin-right:15%;
               border:1px solid var(--line); }
  .meta { color:var(--dim); font-size:11px; margin:2px 6px; }
  form { display:flex; gap:8px; padding:12px 16px; max-width:860px;
         width:100%; margin:0 auto; }
  textarea { flex:1; resize:none; padding:10px; height:48px; }
  button { padding:0 18px; cursor:pointer; }
  button.primary { background:var(--accent); color:#0b0d12; border:none;
                   font-weight:600; }
</style>
</head>
<body>
<header>
  <h1>Local<span>AI</span>-TPU</h1>
  <select id="model"></select>
  <button id="clear" title="clear conversation">Clear</button>
  <div id="status"></div>
</header>
<div id="log"></div>
<form id="f">
  <textarea id="inp" placeholder="Send a message… (Enter to send, Shift+Enter for newline)"></textarea>
  <button class="primary" type="submit" id="send">Send</button>
</form>
<script>
const log = document.getElementById('log');
const modelSel = document.getElementById('model');
const statusEl = document.getElementById('status');
let history = [];

async function loadModels() {
  try {
    const r = await fetch('/v1/models');
    const j = await r.json();
    modelSel.innerHTML = '';
    for (const m of j.data) {
      const o = document.createElement('option');
      o.value = o.textContent = m.id;
      modelSel.appendChild(o);
    }
    statusEl.textContent = j.data.length + ' model(s)';
  } catch (e) { statusEl.textContent = 'server unreachable'; }
}

function add(role, text) {
  const d = document.createElement('div');
  d.className = 'msg ' + role;
  d.textContent = text;
  log.appendChild(d);
  log.scrollTop = log.scrollHeight;
  return d;
}

async function send(text) {
  history.push({role: 'user', content: text});
  add('user', text);
  const out = add('assistant', '');
  const t0 = performance.now();
  document.getElementById('send').disabled = true;
  try {
    const r = await fetch('/v1/chat/completions', {
      method: 'POST', headers: {'Content-Type': 'application/json'},
      body: JSON.stringify({model: modelSel.value, messages: history,
                            stream: true})});
    if (!r.ok) { out.textContent = 'error: ' + await r.text(); return; }
    const reader = r.body.getReader();
    const dec = new TextDecoder();
    let buf = '', content = '', usage = null;
    for (;;) {
      const {done, value} = await reader.read();
      if (done) break;
      buf += dec.decode(value, {stream: true});
      let i;
      while ((i = buf.indexOf('\\n\\n')) >= 0) {
        const line = buf.slice(0, i).trim(); buf = buf.slice(i + 2);
        if (!line.startsWith('data: ')) continue;
        const payload = line.slice(6);
        if (payload === '[DONE]') continue;
        const obj = JSON.parse(payload);
        if (obj.usage) usage = obj.usage;
        const delta = obj.choices && obj.choices[0] && obj.choices[0].delta;
        if (delta && delta.content) {
          content += delta.content;
          out.textContent = content;
          log.scrollTop = log.scrollHeight;
        }
      }
    }
    history.push({role: 'assistant', content});
    const dt = ((performance.now() - t0) / 1000).toFixed(1);
    const meta = document.createElement('div');
    meta.className = 'meta';
    meta.textContent = dt + 's' + (usage ?
      ' · ' + usage.completion_tokens + ' tokens' : '');
    log.appendChild(meta);
  } finally {
    document.getElementById('send').disabled = false;
  }
}

document.getElementById('f').addEventListener('submit', e => {
  e.preventDefault();
  const t = document.getElementById('inp').value.trim();
  if (!t) return;
  document.getElementById('inp').value = '';
  send(t);
});
document.getElementById('inp').addEventListener('keydown', e => {
  if (e.key === 'Enter' && !e.shiftKey) {
    e.preventDefault();
    document.getElementById('f').requestSubmit();
  }
});
document.getElementById('clear').addEventListener('click', () => {
  history = []; log.innerHTML = '';
});
loadModels();
</script>
</body>
</html>
"""
