from localai_tpu.server.http import API, run_server  # noqa: F401
