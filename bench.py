"""Serving benchmark — the measured answer to BASELINE.md (reference publishes
no numbers; protocol = median of >=5 timed windows after warmup).

Measures the continuous-batching Engine end-to-end on whatever accelerator is
attached (one TPU chip under the driver; CPU with --cpu for local runs):
steady-state decode throughput with all slots busy, p50 TTFT through the
prefill bucket, and MFU derived from the model's FLOPs/token.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
vs_baseline is value / 1000 tok/s/chip — the BASELINE.md north star.
"""
from __future__ import annotations

import argparse
import json
import statistics
import sys
import time


def flagship_config(size: str):
    from localai_tpu.models.llama import LlamaConfig

    if size == "tiny":  # CPU smoke config
        return LlamaConfig(vocab_size=512, hidden_size=128,
                           intermediate_size=256, num_layers=2, num_heads=4,
                           num_kv_heads=2, head_dim=32, max_position=512,
                           tie_embeddings=True, dtype="float32")
    if size == "1b":  # Llama-3.2-1B geometry
        return LlamaConfig(vocab_size=128256, hidden_size=2048,
                           intermediate_size=8192, num_layers=16, num_heads=32,
                           num_kv_heads=8, head_dim=64, max_position=4096,
                           rope_base=500000.0, tie_embeddings=True,
                           dtype="bfloat16")
    if size == "3b":  # Llama-3.2-3B geometry
        return LlamaConfig(vocab_size=128256, hidden_size=3072,
                           intermediate_size=8192, num_layers=28, num_heads=24,
                           num_kv_heads=8, head_dim=128, max_position=4096,
                           rope_base=500000.0, tie_embeddings=True,
                           dtype="bfloat16")
    raise ValueError(size)


def param_count(cfg) -> int:
    h, i, L, v = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers, cfg.vocab_size
    qk = cfg.num_heads * cfg.head_dim
    kv = cfg.num_kv_heads * cfg.head_dim
    per_layer = h * qk + 2 * h * kv + qk * h + 3 * h * i + 2 * h
    return v * h * (1 if cfg.tie_embeddings else 2) + L * per_layer + h


def peak_flops_per_chip() -> float:
    """bf16 peak for the attached accelerator (v5e 197 TF/s, v6e 918;
    CPU: nominal 100 GF/s so MFU stays meaningful in smoke runs)."""
    import jax

    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "cpu").lower()
    if "v6" in kind:
        return 918e12
    if "v5p" in kind:
        return 459e12
    if "v5" in kind:
        return 197e12
    if "v4" in kind:
        return 275e12
    if "cpu" in kind or d.platform == "cpu":
        return 100e9
    return 197e12


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--size", default=None, help="tiny|1b|3b (default: by platform)")
    p.add_argument("--cpu", action="store_true", help="force CPU (local smoke)")
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--prompt-len", type=int, default=120)
    p.add_argument("--decode-steps", type=int, default=128)
    p.add_argument("--windows", type=int, default=5)
    p.add_argument("--context", type=int, default=1024)
    args = p.parse_args(argv)

    def note(msg):
        print(f"[bench] {msg}", file=sys.stderr, flush=True)

    # Probe accelerator init in a subprocess first: a dead TPU tunnel hangs
    # jax.devices() forever, and a hung bench records nothing. A CPU fallback
    # keeps the harness producing numbers, but they are marked non-comparable
    # (vs_baseline null) and the probe's failure is recorded, not swallowed.
    import os

    use_cpu = args.cpu
    probe_error = ""
    if not use_cpu:
        import subprocess

        probe_timeout = int(os.environ.get("BENCH_PROBE_TIMEOUT_S", "900"))
        note(f"probing accelerator ({probe_timeout}s limit)...")
        code = ("import time,jax; t=time.time(); d=jax.devices()[0]; "
                "print('PROBE_OK', d.platform, getattr(d,'device_kind',''), "
                "f'{time.time()-t:.0f}s', flush=True)")
        try:
            probe = subprocess.run([sys.executable, "-c", code],
                                   capture_output=True, text=True,
                                   timeout=probe_timeout)
            ok = [l for l in (probe.stdout or "").splitlines()
                  if l.startswith("PROBE_OK")]
            if probe.returncode != 0 or not ok:
                tail = (probe.stderr or "").strip().splitlines()[-8:]
                probe_error = f"rc={probe.returncode}: " + " | ".join(tail)
                note(f"probe FAILED — {probe_error}")
                note("falling back to CPU (results will be non-comparable)")
                use_cpu = True
            else:
                note(f"probe ok: {ok[-1]}")
        except subprocess.TimeoutExpired as e:
            tail = ""
            for s in (e.stderr, e.stdout):
                if s:
                    s = s if isinstance(s, str) else s.decode(errors="replace")
                    tail += " | ".join(s.strip().splitlines()[-4:])
            probe_error = f"init timed out after {probe_timeout}s: {tail}"
            note(f"probe TIMED OUT — {probe_error}")
            note("falling back to CPU (results will be non-comparable)")
            use_cpu = True

    import jax

    if use_cpu:
        jax.config.update("jax_platforms", "cpu")
    note("initializing device client...")
    dev = jax.devices()[0]
    on_cpu = dev.platform == "cpu"
    size = args.size or ("tiny" if on_cpu else "1b")

    import numpy as np

    from localai_tpu.engine import Engine, EngineConfig, GenRequest
    from localai_tpu.models.llama import init_params
    from localai_tpu.ops.sampling import SamplingParams

    note(f"device={getattr(dev, 'device_kind', dev.platform)} size={size}")
    cfg = flagship_config(size)
    context = min(args.context, cfg.max_position)
    params = init_params(cfg, jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    note("params initialized")

    eng = Engine(cfg, params, None, EngineConfig(
        max_slots=args.slots, max_context=context,
        prefill_buckets=(128, min(512, context)),
        prefill_chunk=min(512, context),
    ))
    rng = np.random.default_rng(0)

    def req(n_tokens):
        return GenRequest(
            prompt_ids=rng.integers(1, cfg.vocab_size, args.prompt_len).tolist(),
            params=SamplingParams(temperature=0.8, top_k=40, seed=int(rng.integers(1 << 30))),
            max_tokens=n_tokens, ignore_eos=True)

    # --- warmup: compile prefill bucket + decode step, run a few tokens
    t0 = time.perf_counter()
    for _ in range(args.slots):
        eng.submit(req(4))
    while eng.step():
        pass
    note(f"warmup (compile) done in {time.perf_counter() - t0:.1f}s")

    # --- TTFT: submit one request into the idle engine, time to first token
    ttfts = []
    for _ in range(args.windows):
        rid, out = eng.submit(req(2))
        t0 = time.perf_counter()
        while out.empty():
            eng.step()
        ttfts.append((time.perf_counter() - t0) * 1e3)
        while eng.step():
            pass
    ttft_ms = statistics.median(ttfts)
    note(f"ttft done: {ttft_ms:.1f}ms")

    # --- steady-state decode: all slots busy for the whole window
    tput = []
    for _ in range(args.windows):
        for _ in range(args.slots):
            eng.submit(req(args.decode_steps))
        while not all(s is not None for s in eng._slots):
            eng.step()
        n0 = eng.metrics["tokens_generated"]
        t0 = time.perf_counter()
        # time only fully-batched steps
        steps = max(1, args.decode_steps - 8)
        for _ in range(steps):
            eng.step()
        dt = time.perf_counter() - t0
        tput.append((eng.metrics["tokens_generated"] - n0) / dt)
        while eng.step():
            pass
    toks_per_s = statistics.median(tput)

    n_params = param_count(cfg)
    mfu = (toks_per_s * 2 * n_params) / peak_flops_per_chip()

    # BASELINE.md's north star is tok/s/chip for the flagship on a REAL chip:
    # a CPU run is a harness smoke, not a comparable number.
    result = {
        "metric": f"decode tok/s/chip (llama-{size}, {args.slots} slots, ctx {context})",
        "value": round(toks_per_s, 2),
        "unit": "tok/s",
        "vs_baseline": None if on_cpu else round(toks_per_s / 1000.0, 4),
        "ttft_p50_ms": round(ttft_ms, 2),
        "mfu": None if on_cpu else round(mfu, 4),
        "device": getattr(dev, "device_kind", dev.platform),
        "params": n_params,
    }
    if on_cpu and not args.cpu:
        result["probe_error"] = probe_error[:500]
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
