"""Serving benchmark — the measured answer to BASELINE.md (reference publishes
no numbers; protocol = median of >=5 timed windows after warmup).

Default mode measures THE SERVING PATH: a real backend subprocess spawned by
the ModelManager, driven over gRPC PredictStream — the same surface an HTTP
request rides (BASELINE.md configs #2/#3 ask for the served path, not an
in-process loop). `--mode engine` keeps the in-process Engine measurement.

The flagship geometry is `8b` (Llama-3.1-8B); bf16 8B does not fit a 16GB
v5e chip, so 8b defaults to int8 weights (the GGUF-quant-analog path the
reference's llama.cpp backend also serves with). Checkpoints are synthetic:
config.json declares the geometry and the loader inits weights on device
(engine/loader.py _synthetic_params) — measuring compute, not disk.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
vs_baseline is value / 1000 tok/s/chip — the BASELINE.md north star.
"""
from __future__ import annotations

import argparse
import json
import os
import queue
import statistics
import sys
import tempfile
import threading
import time


RUNS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_runs")

SIZES = {
    # geometry dicts are HF config.json bodies (synthetic checkpoints)
    "tiny": dict(vocab_size=512, hidden_size=128, intermediate_size=256,
                 num_hidden_layers=2, num_attention_heads=4,
                 num_key_value_heads=2, head_dim=32,
                 max_position_embeddings=512, tie_word_embeddings=True),
    "1b": dict(vocab_size=128256, hidden_size=2048, intermediate_size=8192,
               num_hidden_layers=16, num_attention_heads=32,
               num_key_value_heads=8, head_dim=64,
               max_position_embeddings=4096, rope_theta=500000.0,
               tie_word_embeddings=True),
    "3b": dict(vocab_size=128256, hidden_size=3072, intermediate_size=8192,
               num_hidden_layers=28, num_attention_heads=24,
               num_key_value_heads=8, head_dim=128,
               max_position_embeddings=4096, rope_theta=500000.0,
               tie_word_embeddings=True),
    "8b": dict(vocab_size=128256, hidden_size=4096, intermediate_size=14336,
               num_hidden_layers=32, num_attention_heads=32,
               num_key_value_heads=8, head_dim=128,
               max_position_embeddings=8192, rope_theta=500000.0,
               tie_word_embeddings=False),
}


def note(msg):
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def dispatch_stats(metrics: dict) -> dict:
    """Dispatch-fusing scoreboard fields from an engine metrics map (local
    dict or the GetMetrics RPC payload): decode dispatch count, fused
    steps/dispatch, and host-sync wait per generated token. These are the
    numbers the single-dispatch decode loop moves — promoted into the bench
    JSON line so the scoreboard can gate on them."""
    d = int(metrics.get("decode_dispatches", 0))
    s = int(metrics.get("decode_steps_dispatched", 0))
    toks = int(metrics.get("tokens_generated", 0))
    wait = float(metrics.get("host_sync_wait_ms", 0.0))
    return {
        "decode_dispatches": d,
        "decode_steps_dispatched": s,
        "steps_per_dispatch": round(s / max(d, 1), 2),
        "host_sync_wait_ms_per_token": round(wait / max(toks, 1), 4),
    }


def slo_stats(metrics: dict) -> dict:
    """SLO scoreboard fields (ttft_p95_ms / tpot_p50_ms / queue_wait_p50_ms)
    rebuilt from the engine's own streaming histograms (`hist_*` GetMetrics
    keys or the in-process registry's flat() map) — engine-measured, not a
    host stopwatch around the RPC (ISSUE 11)."""
    try:
        from localai_tpu.telemetry import parse_flat, snapshot_from_hists

        snap = snapshot_from_hists(parse_flat(metrics))
    except Exception:
        return {}
    out = {}
    ttft = snap.get("ttft") or {}
    tpot = snap.get("tpot") or {}
    qw = snap.get("queue_wait") or {}
    if ttft.get("count"):
        out["ttft_p95_ms"] = round(ttft["p95_ms"], 3)
    if tpot.get("count"):
        out["tpot_p50_ms"] = round(tpot["p50_ms"], 4)
    if qw.get("count"):
        out["queue_wait_p50_ms"] = round(qw["p50_ms"], 4)
    return out


def sched_base(eng):
    """Snapshot an engine's tick-ledger counters + token count before the
    measured windows, so sched_stats can report window deltas (the compile
    bursts between warmup and the windows also dispatch)."""
    sched = getattr(eng, "_sched", None)
    if sched is None:
        return None
    return (dict(sched.counters), dict(sched.variants),
            int(eng.metrics.get("tokens_generated", 0)))


def sched_stats(eng, base=None, *, toks_per_s=0.0, device_kind="",
                chips=1) -> dict:
    """Scheduler X-ray scoreboard fields (ISSUE 13) from a live engine:
    tick-ledger aggregates (budget utilization, pad-row fraction, reason-
    code counts, per-variant dispatch counts — deltas vs `base` when given)
    plus the per-variant cost-analysis rooflines. When a throughput is
    given, also computes the cost-backed `mfu`: measured tok/s times the
    XLA-modeled FLOPs per generated token (sum of each variant's compiled
    cost weighted by its dispatch count), over the chip peak — replacing
    the old 2*N*tokens guess. rooflines() runs AFTER the measured windows
    (AOT lowering is off the timed path and never touches the jit cache)."""
    sched = getattr(eng, "_sched", None)
    if sched is None:
        return {}
    try:
        roofs = eng.rooflines()
    except Exception:
        roofs = {}
    c0, v0, t0 = base or ({}, {}, 0)
    reasons = {k: n - c0.get(k, 0) for k, n in sched.counters.items()
               if n - c0.get(k, 0)}
    variants = {k: n - v0.get(k, 0) for k, n in sched.variants.items()
                if n - v0.get(k, 0)}
    toks = int(eng.metrics.get("tokens_generated", 0)) - t0
    out = {
        "budget_utilization": round(sched.budget_utilization(), 4),
        "pad_rows_frac": round(sched.pad_rows_frac(), 4),
        "reason_codes": reasons,
        "sched_variants": variants,
    }
    if roofs:
        out["rooflines"] = {
            name: {"cost_flops": r.get("cost_flops", 0.0),
                   "cost_bytes": r.get("cost_bytes", 0.0),
                   "bound": r.get("bound", ""),
                   "mfu_ceiling": round(r.get("mfu", 0.0), 4)}
            for name, r in roofs.items()}
        flops = sum((roofs.get(v) or {}).get("cost_flops", 0.0) * n
                    for v, n in variants.items())
        if flops > 0 and toks > 0 and toks_per_s > 0:
            peak = peak_flops_per_chip(device_kind) * max(chips, 1)
            out["mfu"] = round(toks_per_s * (flops / toks) / peak, 4)
    return out


# ---------------------------------------------------------- run artifacts
# The scoreboard contract (ROADMAP open item #1 / VERDICT round-5 ask #1):
# BENCH_rN.json must never print `device: cpu` while a real on-chip artifact
# exists. Every on-accelerator run is archived under bench_runs/; when the
# TPU probe fails or finds only CPU, the freshest archived TPU artifact is
# re-emitted with `stale: true` and its original timestamp instead of a
# non-comparable CPU number.

def _is_tpu_device(device) -> bool:
    d = str(device or "").lower()
    return bool(d) and "cpu" not in d


def save_artifact(result: dict, runs_dir: str = "") -> str | None:
    """Archive an on-accelerator result JSON under bench_runs/ (no-op for
    CPU results — only real chip numbers feed the stale fallback)."""
    if not _is_tpu_device(result.get("device")):
        return None
    runs_dir = runs_dir or os.environ.get("BENCH_RUNS_DIR", RUNS_DIR)
    try:
        os.makedirs(runs_dir, exist_ok=True)
        art = dict(result, recorded_at=time.strftime(
            "%Y-%m-%dT%H:%M:%S%z", time.localtime()))
        path = os.path.join(
            runs_dir, f"bench_{time.strftime('%Y%m%d_%H%M%S')}.json")
        with open(path, "w") as fh:
            json.dump(art, fh, indent=1)
        note(f"archived artifact -> {path}")
        return path
    except OSError as e:
        note(f"artifact archive failed ({e}) — result still printed")
        return None


def latest_tpu_artifact(runs_dir: str = "") -> tuple[dict, str] | None:
    """Newest archived artifact whose device is a real accelerator, or None.
    Ordering: the `recorded_at` stamp when present, file mtime otherwise."""
    runs_dir = runs_dir or os.environ.get("BENCH_RUNS_DIR", RUNS_DIR)
    best = None
    if not os.path.isdir(runs_dir):
        return None
    for fname in os.listdir(runs_dir):
        if not fname.endswith(".json"):
            continue
        path = os.path.join(runs_dir, fname)
        try:
            with open(path) as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            continue
        if not isinstance(data, dict) or not _is_tpu_device(
                data.get("device")):
            continue
        key = (data.get("recorded_at") or "", os.path.getmtime(path))
        if best is None or key > best[0]:
            best = (key, data, path)
    return (best[1], best[2]) if best else None


def emit_stale_artifact(art: dict, path: str, probe_error: str,
                        probe_report: dict | None = None) -> None:
    """Print the archived on-chip result as THE scoreboard line, flagged
    stale — never a CPU number when a real TPU artifact exists. The probe
    report rides along so a stale line still says exactly WHERE this run's
    chip init wedged (phase + thread stacks)."""
    out = dict(art)
    out["stale"] = True
    out["stale_source"] = os.path.basename(path)
    if probe_error:
        out["probe_error"] = probe_error[:500]
    if probe_report is not None:
        out["probe_report"] = probe_report
    note(f"TPU unreachable — surfacing stale on-chip artifact "
         f"{out['stale_source']} (recorded {out.get('recorded_at', '?')})")
    print(json.dumps(out))


def ensure_virtual_devices(n: int) -> None:
    """Force an n-device CPU host platform (mode tp's virtual mesh). Must run
    BEFORE jax initializes — XLA_FLAGS is read when the CPU client is
    created; an existing forced count (e.g. the test harness's 8) wins."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()


def build_tp_mesh(tp: int):
    """('data'=1, 'model'=tp) mesh over the first tp devices."""
    import jax

    from localai_tpu.parallel.mesh import MeshConfig, build_mesh

    return build_mesh(MeshConfig(data=1, model=tp), jax.devices()[:tp])


def write_synthetic_checkpoint(size: str, path: str) -> str:
    body = dict(SIZES[size])
    body.update(architectures=["LlamaForCausalLM"], rms_norm_eps=1e-5,
                localai_synthetic=True)
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "config.json"), "w") as fh:
        json.dump(body, fh)
    return path


def param_count(size: str) -> int:
    g = SIZES[size]
    h, i = g["hidden_size"], g["intermediate_size"]
    L, v = g["num_hidden_layers"], g["vocab_size"]
    hd = g.get("head_dim") or h // g["num_attention_heads"]
    qk = g["num_attention_heads"] * hd
    kv = g["num_key_value_heads"] * hd
    per_layer = h * qk + 2 * h * kv + qk * h + 3 * h * i + 2 * h
    return v * h * (1 if g.get("tie_word_embeddings") else 2) + L * per_layer + h


def peak_flops_per_chip(kind: str) -> float:
    """bf16 peak for the attached accelerator (v5e 197 TF/s, v6e 918;
    CPU: nominal 100 GF/s so MFU stays meaningful in smoke runs)."""
    kind = kind.lower()
    if "v6" in kind:
        return 918e12
    if "v5p" in kind:
        return 459e12
    if "v5" in kind:
        return 197e12
    if "v4" in kind:
        return 275e12
    if "cpu" in kind:
        return 100e9
    return 197e12


# the debuggable chip probe (ISSUE 11): init broken into named phases, each
# announced on stdout the moment it STARTS, so a wedged init says exactly
# where it wedged (plugin handshake vs client init vs first transfer vs
# first compile). faulthandler arms a watchdog that dumps EVERY thread's
# stack to stderr and exits just before the parent's timeout — the stacks
# land in the probe report instead of dying with the child.
PROBE_PHASES = ("plugin_handshake", "client_init", "first_device_put",
                "first_compile")

_PROBE_CHILD = r"""
import faulthandler, sys, time
t0 = time.time()

def phase(name):
    print(f"PROBE_PHASE {name} {time.time()-t0:.1f}s", flush=True)

faulthandler.dump_traceback_later(float(sys.argv[1]), exit=True)
phase("plugin_handshake")   # importing jax registers the PJRT plugin
import jax
phase("client_init")        # first jax.devices() builds the PJRT client
d = jax.devices()[0]
phase("first_device_put")   # first host->device transfer
import numpy as np
x = jax.device_put(np.ones((8,), np.float32))
jax.block_until_ready(x)
phase("first_compile")      # first XLA compile + execute
jax.block_until_ready(jax.jit(lambda a: a * 2.0)(x))
faulthandler.cancel_dump_traceback_later()
print("PROBE_OK", d.platform, getattr(d, "device_kind", ""),
      f"{time.time()-t0:.0f}s", flush=True)
"""


def _run_probe_once(timeout_s: int, compile_cache: str) -> dict:
    """One probe child under a heartbeat: stdout is read incrementally so
    phase transitions surface live on stderr, and the attempt record keeps
    the phase timings plus the faulthandler stack dump on a hang."""
    import subprocess

    env = dict(os.environ)
    if compile_cache:
        # persistent XLA compilation cache: a warm cache turns the
        # first_compile phase from minutes into seconds on repeat runs
        env["JAX_COMPILATION_CACHE_DIR"] = compile_cache
    # the child's own watchdog fires before the parent timeout so the stack
    # dump reaches stderr while the pipe is still alive
    child_limit = max(10, timeout_s - 5)
    proc = subprocess.Popen(
        [sys.executable, "-c", _PROBE_CHILD, str(child_limit)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
    phases: dict[str, float] = {}
    ok_lines: list[str] = []
    stderr_buf: list[str] = []

    def _stdout_reader():
        for line in proc.stdout:
            line = line.strip()
            if line.startswith("PROBE_PHASE"):
                parts = line.split()
                if len(parts) >= 3:
                    try:
                        phases[parts[1]] = float(parts[2].rstrip("s"))
                    except ValueError:
                        phases[parts[1]] = -1.0
                    note(f"probe phase: {parts[1]} (+{parts[2]})")
            elif line.startswith("PROBE_OK"):
                ok_lines.append(line)

    def _stderr_reader():
        stderr_buf.append(proc.stderr.read() or "")

    readers = [threading.Thread(target=_stdout_reader, daemon=True),
               threading.Thread(target=_stderr_reader, daemon=True)]
    [t.start() for t in readers]
    timed_out = False
    try:
        rc = proc.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        timed_out = True
        proc.kill()
        rc = proc.wait()
    [t.join(timeout=5) for t in readers]
    stderr = stderr_buf[0] if stderr_buf else ""
    # the faulthandler watchdog exits rc=1 after printing "Timeout (...)!"
    # plus every thread's stack — that IS a timeout, not a crash
    timed_out = timed_out or "Timeout (" in stderr
    done = [p for p in PROBE_PHASES if p in phases]
    attempt = {
        "timeout_s": timeout_s,
        "rc": rc,
        "timed_out": timed_out,
        "ok": bool(ok_lines),
        "phases_s": phases,
        "last_phase": done[-1] if done else "",
    }
    if ok_lines:
        parts = ok_lines[-1].split()
        attempt["platform"] = parts[1]
        attempt["device_kind"] = " ".join(parts[2:-1]) or parts[1]
        attempt["init_s"] = phases.get("first_compile", 0.0)
    else:
        # not ok: the last announced phase is the one it died/stuck in
        attempt["stuck_phase"] = done[-1] if done else "spawn"
        attempt["stack_dump"] = stderr[-4000:]
    return attempt


# keepalive probe child: the plain probe script plus a stdin command loop —
# after init, PING answers PROBE_ALIVE using the ALREADY-BUILT PJRT client
# (no re-handshake, no re-init), QUIT exits cleanly
_KEEPALIVE_CHILD = _PROBE_CHILD + r"""
for _line in sys.stdin:
    _cmd = _line.strip()
    if _cmd == "PING":
        # jax.devices() on a live client is a cached lookup — if the
        # tunnel died the call raises and the child exits non-zero
        _d = jax.devices()[0]
        print("PROBE_ALIVE", _d.platform, getattr(_d, "device_kind", ""),
              flush=True)
    elif _cmd == "QUIT":
        break
"""


class ProbeKeepalive:
    """One probe child kept alive across bench modes (--probe-keepalive):
    the child pays plugin handshake / client init / first compile ONCE,
    then answers PING over stdin in milliseconds using the pre-initialized
    device client. A chip ladder that probes before every mode stops
    re-paying (and re-hanging on) cold init — the ROADMAP measurement
    un-blocker for the stalled 'axon' runs."""

    def __init__(self, timeout_s: int, compile_cache: str = ""):
        import subprocess

        env = dict(os.environ)
        if compile_cache:
            env["JAX_COMPILATION_CACHE_DIR"] = compile_cache
        self.timeout_s = timeout_s
        self.platform = ""
        self.device_kind = ""
        self._lines: queue.Queue[str] = queue.Queue()
        self.proc = subprocess.Popen(
            [sys.executable, "-c", _KEEPALIVE_CHILD,
             str(max(10, timeout_s - 5))],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, env=env)
        threading.Thread(target=self._reader, daemon=True).start()

    def _reader(self):
        for line in self.proc.stdout:
            self._lines.put(line.strip())

    def alive(self) -> bool:
        return self.proc.poll() is None

    def start(self) -> dict:
        """Block until the child finishes its init phases (or the budget
        runs out); returns an attempt record shaped like _run_probe_once's
        so it slots into the same probe report."""
        deadline = time.time() + self.timeout_s
        phases: dict[str, float] = {}
        attempt: dict = {"timeout_s": self.timeout_s, "keepalive": True,
                         "rc": None, "timed_out": False, "ok": False,
                         "phases_s": phases}
        while time.time() < deadline:
            try:
                line = self._lines.get(timeout=0.5)
            except queue.Empty:
                if not self.alive():
                    break
                continue
            if line.startswith("PROBE_PHASE"):
                parts = line.split()
                if len(parts) >= 3:
                    try:
                        phases[parts[1]] = float(parts[2].rstrip("s"))
                    except ValueError:
                        phases[parts[1]] = -1.0
                    note(f"probe phase: {parts[1]} (+{parts[2]})")
            elif line.startswith("PROBE_OK"):
                parts = line.split()
                self.platform = parts[1]
                self.device_kind = " ".join(parts[2:-1]) or parts[1]
                attempt.update(ok=True, platform=self.platform,
                               device_kind=self.device_kind,
                               init_s=phases.get("first_compile", 0.0))
                return attempt
        done = [p for p in PROBE_PHASES if p in phases]
        attempt.update(timed_out=self.alive(), rc=self.proc.poll(),
                       stuck_phase=done[-1] if done else "spawn",
                       last_phase=done[-1] if done else "")
        self.close()
        return attempt

    def ping(self, timeout_s: float = 30.0) -> bool:
        """Reuse check: True iff the live child's device client still
        answers. False (dead child, broken pipe, silence) means the caller
        should close() and cold-probe again."""
        if not self.alive():
            return False
        try:
            self.proc.stdin.write("PING\n")
            self.proc.stdin.flush()
        except (BrokenPipeError, OSError):
            return False
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            try:
                line = self._lines.get(timeout=0.5)
            except queue.Empty:
                if not self.alive():
                    return False
                continue
            if line.startswith("PROBE_ALIVE"):
                return True
        return False

    def close(self):
        if self.proc.poll() is None:
            try:
                self.proc.stdin.write("QUIT\n")
                self.proc.stdin.flush()
            except (BrokenPipeError, OSError):
                pass
            try:
                self.proc.wait(timeout=5)
            except Exception:
                self.proc.kill()
                self.proc.wait()


# the process-wide keepalive child (when --probe-keepalive): one per
# process, shared by every probe_accelerator call — a driver running
# several modes through main() pays cold init exactly once
_KEEPALIVE: ProbeKeepalive | None = None


def probe_accelerator(args) -> tuple[bool, str, str]:
    """Probe accelerator init in a subprocess: a dead TPU tunnel hangs
    jax.devices() forever, and a hung bench records nothing. The parent must
    NEVER init JAX itself in serve mode — it would hold the chip and starve
    the backend subprocess — so the probe also reports the device kind.
    Returns (use_cpu, probe_error, device_kind); the full phased report
    (per-attempt phase timings + stack dumps) lands on args.probe_report and
    is embedded in every result artifact."""
    report: dict = {
        "attempts": [],
        "ok": False,
        "single_attempt": bool(getattr(args, "probe_single_attempt", False)),
        "compile_cache": getattr(args, "probe_compile_cache", "") or "",
        "phases": list(PROBE_PHASES),
    }
    args.probe_report = report
    if args.cpu:
        report["ok"] = True
        report["device"] = "cpu"
        return True, "", "cpu"

    total = (getattr(args, "probe_timeout", 0)
             or int(os.environ.get("BENCH_PROBE_TIMEOUT_S", "900")))
    if getattr(args, "probe_keepalive", False):
        global _KEEPALIVE
        if _KEEPALIVE is not None:
            # reuse path: the earlier mode's child still holds a live
            # device client — a PING round-trip replaces the cold ladder
            if _KEEPALIVE.ping():
                report["ok"] = True
                report["keepalive_reused"] = True
                report["device"] = _KEEPALIVE.device_kind
                note(f"probe keepalive: reusing live client "
                     f"({_KEEPALIVE.device_kind})")
                if _KEEPALIVE.platform == "cpu":
                    return True, "", "cpu"
                return False, "", _KEEPALIVE.device_kind
            note("probe keepalive: child died — cold-probing again")
            _KEEPALIVE.close()
            _KEEPALIVE = None
        _KEEPALIVE = ProbeKeepalive(max(60, total),
                                    report["compile_cache"])
        a = _KEEPALIVE.start()
        report["attempts"].append(a)
        if a["ok"]:
            note(f"probe ok: {a['device_kind']} in "
                 f"{a.get('init_s', 0):.0f}s (keepalive child stays up)")
            report["ok"] = True
            report["device"] = a["device_kind"]
            if a["platform"] == "cpu":
                note("probe found only CPU — results will be "
                     "non-comparable")
                return True, "", "cpu"
            return False, "", a["device_kind"]
        _KEEPALIVE = None
        err = (f"keepalive probe died in phase "
               f"{a.get('stuck_phase', 'spawn')} "
               f"(timeout={a['timed_out']})")
        note(f"probe FAILED — {err}; "
             "falling back to CPU (results will be non-comparable)")
        report["error"] = err
        return True, err, "cpu"
    if report["single_attempt"]:
        # one long attempt: a legitimately slow cold init (big compile, cold
        # plugin) gets the whole budget instead of dying on ladder rungs
        ladder = [max(60, total)]
    else:
        # a flaky tunnel can hang one client-creation attempt and accept the
        # next — split the budget into escalating attempts (the last one
        # long enough for a legitimately slow cold init)
        ladder = [max(60, int(total * f)) for f in (0.25, 0.25, 0.5)]
    err = ""
    hard_fails = 0
    for attempt_n, probe_timeout in enumerate(ladder, 1):
        note(f"probing accelerator (attempt {attempt_n}/{len(ladder)}, "
             f"{probe_timeout}s limit)...")
        a = _run_probe_once(probe_timeout, report["compile_cache"])
        report["attempts"].append(a)
        if a["ok"]:
            note(f"probe ok: {a['device_kind']} in {a.get('init_s', 0):.0f}s")
            report["ok"] = True
            report["device"] = a["device_kind"]
            if a["platform"] == "cpu":
                # a TPU-less machine: run the CPU smoke, never publish it as
                # a comparable per-chip number
                note("probe found only CPU — results will be non-comparable")
                return True, "", "cpu"
            return False, "", a["device_kind"]
        if a["timed_out"]:
            err = (f"init timed out after {probe_timeout}s in phase "
                   f"{a['stuck_phase']} (reached: "
                   f"{', '.join(a['phases_s']) or 'none'}); thread stacks "
                   f"in probe_report")
            note(f"probe TIMED OUT — {err}")
        else:
            tail = " | ".join(
                (a.get("stack_dump") or "").strip().splitlines()[-8:])
            err = f"rc={a['rc']} in phase {a['stuck_phase']}: {tail}"
            note(f"probe FAILED — {err}")
            # fast non-timeout failures are usually deterministic
            # (missing libtpu etc.) — one retry covers the transient
            # connection-refused case, then stop burning the budget
            hard_fails += 1
            if hard_fails >= 2:
                break
    note("falling back to CPU (results will be non-comparable)")
    report["error"] = err
    return True, err, "cpu"


# --------------------------------------------------------------- serve mode

def bench_serve(args, size: str, on_cpu: bool):
    """Measure through the real process boundary: ModelManager-spawned gRPC
    backend, PredictStream per request (what /v1/chat/completions rides)."""
    import numpy as np

    from localai_tpu.config import AppConfig, ModelConfig
    from localai_tpu.core.manager import ModelManager

    tmp = tempfile.mkdtemp(prefix="bench-ckpt-")
    ckpt = write_synthetic_checkpoint(size, os.path.join(tmp, size))
    os.environ["LOCALAI_ALLOW_SYNTHETIC"] = "1"  # inherited by the backend
    # the bench runs its own warmup phase and measures TTFT after it; the
    # backend's LoadModel prewarm would re-pay the same compiles inside the
    # 600 s LoadModel deadline (and on a TPU the grown variant set could
    # blow it) — disable for the spawned backend
    os.environ["LOCALAI_NO_PREWARM"] = "1"
    dtype = args.dtype or ("int8" if size == "8b" else "bfloat16")
    if on_cpu:
        dtype = args.dtype or "float32"
        os.environ["LOCALAI_JAX_PLATFORM"] = "cpu"
    context = min(args.context, SIZES[size]["max_position_embeddings"])

    if args.tensor_parallel > 1 and on_cpu:
        # the backend subprocess inherits os.environ — give it the virtual
        # devices the requested mesh needs
        ensure_virtual_devices(args.tensor_parallel)
    mcfg = ModelConfig.from_dict({
        "name": f"bench-{size}",
        "backend": "llm",
        "context_size": context,
        "parallel": args.slots,
        "dtype": dtype,
        # int8 KV on the quantized-weight geometries: the llama.cpp analog
        # (cache_type q8_0) and what makes high slot counts fit HBM
        "cache_type_k": "int8" if dtype in ("int8", "int4") else "",
        "kv_pages": args.kv_pages,
        "prefill_buckets": [128, min(512, context)],
        "mesh": ({"data": 1, "model": args.tensor_parallel}
                 if args.tensor_parallel > 1 else {}),
        "parameters": {"model": ckpt},
    })
    app = AppConfig(models_path=tmp, parallel_requests=args.slots)
    manager = ModelManager(app)
    note(f"spawning backend subprocess (size={size} dtype={dtype} "
         f"slots={args.slots} ctx={context})...")
    t0 = time.perf_counter()
    handle = manager.load(mcfg)
    note(f"backend ready in {time.perf_counter() - t0:.1f}s")
    vocab = SIZES[size]["vocab_size"]
    seed_counter = iter(range(1, 1 << 30))
    seed_lock = threading.Lock()

    def stream(n_tokens, arrivals=None):
        """One PredictStream request; returns (first_token_t, tokens).
        Each call owns a fresh Generator — np Generators are not
        thread-safe and the steady-state windows run these concurrently."""
        with seed_lock:
            seed = next(seed_counter)
        rng = np.random.default_rng(seed)
        ids = rng.integers(1, vocab, args.prompt_len).tolist()
        first, n = None, 0
        for reply in handle.client.predict_stream(
                prompt_ids=ids, tokens=n_tokens, temperature=0.8, top_k=40,
                seed=seed, ignore_eos=True,
                timeout=3600.0):
            now = time.perf_counter()
            if reply.token_ids:  # token event (synthetic ckpts have no text)
                n += 1
                if first is None:
                    first = now
                if arrivals is not None:
                    arrivals.append(now)
        return first, n

    try:
        # warmup: compile prefill buckets + decode step through the wire
        t0 = time.perf_counter()
        ws = [threading.Thread(target=stream, args=(4,))
              for _ in range(min(2, args.slots))]
        [t.start() for t in ws]
        [t.join() for t in ws]
        stream(4)
        note(f"warmup (compile) done in {time.perf_counter() - t0:.1f}s")

        # TTFT: single request against the idle engine, through gRPC
        ttfts = []
        for _ in range(args.windows):
            t0 = time.perf_counter()
            first, _ = stream(2)
            ttfts.append((first - t0) * 1e3)
        ttft_ms = statistics.median(ttfts)
        note(f"ttft p50 {ttft_ms:.1f}ms over {args.windows} runs")

        # steady-state: all slots streaming concurrently; measure the window
        # where every stream is live (max of firsts .. min of lasts)
        tput = []
        for w in range(args.windows):
            arrivals_per = [[] for _ in range(args.slots)]
            threads = [
                threading.Thread(target=stream,
                                 args=(args.decode_steps, arrivals_per[i]))
                for i in range(args.slots)
            ]
            t0 = time.perf_counter()
            [t.start() for t in threads]
            [t.join() for t in threads]
            wall = time.perf_counter() - t0
            all_arr = sorted(a for arr in arrivals_per for a in arr)
            lo = max(arr[0] for arr in arrivals_per if arr)
            hi = min(arr[-1] for arr in arrivals_per if arr)
            in_window = [a for a in all_arr if lo <= a <= hi]
            if hi > lo and len(in_window) > args.slots:
                tput.append((len(in_window) - 1) / (hi - lo))
            else:  # degenerate window; fall back to wall-clock rate
                tput.append(len(all_arr) / wall)
            note(f"window {w}: {tput[-1]:.1f} tok/s "
                 f"({len(all_arr)} tokens, wall {wall:.1f}s)")
        stats = {}
        try:
            m = handle.client.metrics()
            args.slo_metrics = m   # hist_* keys → emit_result's slo_stats
            stats = dispatch_stats(m)
            d, s = m.get("decode_dispatches", 0), m.get(
                "decode_steps_dispatched", 0)
            note(f"engine: {d:.0f} decode dispatches, {s:.0f} steps "
                 f"({s / max(d, 1):.1f} steps/dispatch), "
                 f"{m.get('admit_dispatches', 0):.0f} admit dispatches, "
                 f"host-sync wait "
                 f"{stats['host_sync_wait_ms_per_token']:.3f} ms/token")
        except Exception:
            pass
        if getattr(args, "trace", False):
            try:   # pull spans + stage profile before the backend dies
                args.trace_payload = handle.client.trace()
            except Exception as e:
                note(f"trace fetch failed: {e}")
        return statistics.median(tput), ttft_ms, context, dtype, stats
    finally:
        manager.stop_all()
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)


# --------------------------------------------------------------- engine mode

def bench_engine(args, size: str, on_cpu: bool, kv_pages: int | None = None,
                 tp: int | None = None):
    """In-process Engine measurement (no RPC overhead) — kernel ceiling.
    `tp` > 1 runs the same workload on a (1, tp) tensor-parallel mesh
    (weights — int8 included — and KV heads sharded on 'model')."""
    import jax
    import numpy as np

    from localai_tpu.engine import Engine, EngineConfig, GenRequest
    from localai_tpu.engine.loader import load_config, load_params
    from localai_tpu.ops.sampling import SamplingParams

    tp = args.tensor_parallel if tp is None else tp
    mesh = build_tp_mesh(tp) if tp and tp > 1 else None
    tmp = tempfile.mkdtemp(prefix="bench-ckpt-")
    ckpt = write_synthetic_checkpoint(size, os.path.join(tmp, size))
    os.environ["LOCALAI_ALLOW_SYNTHETIC"] = "1"
    dtype = args.dtype or ("int8" if size == "8b" else "bfloat16")
    if on_cpu:
        dtype = args.dtype or "float32"
    cfg = load_config(ckpt, dtype=dtype)
    context = min(args.context, cfg.max_position)
    params = load_params(ckpt, cfg, dtype=dtype, mesh=mesh)
    jax.block_until_ready(params)
    note("params initialized" + (f" (sharded over 1x{tp} mesh)" if mesh
                                 else ""))

    eng = Engine(cfg, params, None, EngineConfig(
        max_slots=args.slots, max_context=context,
        prefill_buckets=(128, min(512, context)),
        prefill_chunk=min(512, context),
        mesh=mesh,
        # mirror bench_serve's KV config (was silently dense-bf16 before:
        # 32-slot engine-mode runs OOM'd at admit compile)
        cache_type="int8" if dtype in ("int8", "int4") else "",
        kv_pages=args.kv_pages if kv_pages is None else kv_pages,
        # A/B the single-dispatch decode loop (None = engine default 64;
        # 0 regresses to the scan-block ladder for comparison runs)
        **({} if args.decode_loop is None
           else {"decode_loop": args.decode_loop}),
    ))
    rng = np.random.default_rng(0)

    def req(n_tokens):
        return GenRequest(
            prompt_ids=rng.integers(1, cfg.vocab_size, args.prompt_len).tolist(),
            params=SamplingParams(temperature=0.8, top_k=40,
                                  seed=int(rng.integers(1 << 30))),
            max_tokens=n_tokens, ignore_eos=True)

    # pre-compile the decode-loop variants + remaining ladder widths NOW so
    # window 0 measures steady-state, not mid-stream XLA compiles (the old
    # warmup compiled only the shapes its own short requests happened to hit)
    t0 = time.perf_counter()
    eng.warmup()
    note(f"decode programs pre-compiled in {time.perf_counter() - t0:.1f}s")
    sbase = sched_base(eng)   # ledger just reset; aligns the token counter

    t0 = time.perf_counter()
    for _ in range(args.slots):
        eng.submit(req(4))
    while eng.step():
        pass
    # a lone request admits through the K=1 program — compile it now or the
    # first TTFT probe pays the compile (serve-mode warmup already does this)
    eng.submit(req(4))
    while eng.step():
        pass
    note(f"warmup (compile) done in {time.perf_counter() - t0:.1f}s")

    ttfts = []
    for _ in range(args.windows):
        rid, out = eng.submit(req(2))
        t0 = time.perf_counter()
        while out.empty():
            eng.step()
        ttfts.append((time.perf_counter() - t0) * 1e3)
        while eng.step():
            pass
    ttft_ms = statistics.median(ttfts)
    note(f"ttft done: {ttft_ms:.1f}ms")

    tput = []
    for _ in range(args.windows):
        for _ in range(args.slots):
            eng.submit(req(args.decode_steps))
        while not all(s is not None for s in eng._slots):
            eng.step()
        n0 = eng.metrics["tokens_generated"]
        t0 = time.perf_counter()
        steps = max(1, args.decode_steps - 8)
        for _ in range(steps):
            eng.step()
        dt = time.perf_counter() - t0
        tput.append((eng.metrics["tokens_generated"] - n0) / dt)
        while eng.step():
            pass
    m = eng.metrics
    d = max(m["decode_dispatches"], 1)
    stats = dispatch_stats(m)
    dev0 = jax.devices()[0]
    sstats = sched_stats(
        eng, sbase, toks_per_s=statistics.median(tput),
        device_kind=getattr(dev0, "device_kind", dev0.platform),
        chips=tp if tp and tp > 1 else 1)
    if sstats:
        # cost-backed MFU rides separately so the result sites can place it
        # under the top-level `mfu` key
        stats["mfu_cost"] = sstats.pop("mfu", None)
        stats["sched"] = sstats
    note(f"engine: {m['decode_dispatches']} decode dispatches, "
         f"{m['decode_steps_dispatched']} steps "
         f"({m['decode_steps_dispatched'] / d:.1f} steps/dispatch), "
         f"{m['admit_dispatches']} admit dispatches, "
         f"host-sync wait {stats['host_sync_wait_ms_per_token']:.3f} "
         f"ms/token")
    if getattr(args, "trace", False):
        from localai_tpu import telemetry

        args.trace_payload = {
            "spans": telemetry.chrome_events(),
            "profile": eng._prof.report() if eng._prof is not None else {},
            "pid": os.getpid(),
        }
    import shutil

    shutil.rmtree(tmp, ignore_errors=True)
    return statistics.median(tput), ttft_ms, context, dtype, stats


def bench_paged(args, size: str, on_cpu: bool):
    """Dense vs paged, SAME workload, ONE process — the regression guard
    VERDICT Weak #2 asked for. Runs the in-process engine measurement twice
    (kv_pages=0, then a pool sized for the workload) and reports the ratio:
    a paged_over_dense well below 1.0 is the pool-rematerialization bug
    pattern and must never ship silently again."""
    from localai_tpu.ops.paged import BLOCK

    dense_tps, dense_ttft, context, dtype, _ = bench_engine(
        args, size, on_cpu, kv_pages=0)
    note(f"dense: {dense_tps:.1f} tok/s")
    pages = args.kv_pages
    if not pages:
        # reservation per slot: prompt + max_tokens + the engine's in-flight
        # margin (2*decode_block+1 == 33 at the default block of 16),
        # capped at the context — mirror engine._blocks_for + trash block
        tokens = min(args.prompt_len + args.decode_steps + 33, context)
        pages = args.slots * (-(-tokens // BLOCK)) + 1
    note(f"paged pool: {pages} blocks")
    paged_tps, paged_ttft, _, _, stats = bench_engine(
        args, size, on_cpu, kv_pages=pages)
    note(f"paged: {paged_tps:.1f} tok/s "
         f"({paged_tps / max(dense_tps, 1e-9):.2f}x dense)")
    return (dense_tps, dense_ttft, paged_tps, paged_ttft, pages, context,
            dtype, stats)


# -------------------------------------------------------------- ragged mode

def _ragged_leg(args, cfg, params, context, kv_pages, budget, mixed,
                loop_steps=0):
    """One serving leg for --mode ragged: a `windows`-round burst workload
    (slots requests each, decode_steps tokens each) through one engine.
    Returns serving throughput (generated tok/s over the whole round,
    prefill included — the number continuous batching moves), the
    under-load TTFT distribution, the token-budget utilization, and the
    fused-loop stats (steps/dispatch, exit-reason counts). `loop_steps`
    gates the ISSUE 16 fused multi-step tick: 0 = single-step dispatch
    (the pre-fused behavior the A/B legs baseline against)."""
    import statistics as st

    import numpy as np

    from localai_tpu.engine import Engine, EngineConfig, GenRequest
    from localai_tpu.ops.sampling import SamplingParams

    eng = Engine(cfg, params, None, EngineConfig(
        max_slots=args.slots, max_context=context,
        prefill_buckets=(128, min(512, context)),
        prefill_chunk=min(128, context),
        kv_pages=kv_pages, prompt_cache=False,
        ragged_token_budget=budget,
        ragged_loop_steps=loop_steps,
        **({} if args.decode_loop is None
           else {"decode_loop": args.decode_loop}),
    ))
    rng = np.random.default_rng(0)

    def prompt_lens(k):
        if mixed:
            # 3:1 length spread averaging prompt_len — the ragged pack's
            # whole point is that this costs nothing vs equal lengths
            lo = max(8, args.prompt_len // 2)
            return rng.integers(lo, args.prompt_len * 3 // 2 + 1, k).tolist()
        return [args.prompt_len] * k

    def burst(n_tokens):
        subs = []
        for n in prompt_lens(args.slots):
            _, q = eng.submit(GenRequest(
                rng.integers(1, cfg.vocab_size, n).tolist(),
                SamplingParams(temperature=0.8, top_k=40,
                               seed=int(rng.integers(1 << 30))),
                max_tokens=n_tokens, ignore_eos=True))
            subs.append((time.perf_counter(), q))
        ttfts, n0 = [], eng.metrics["tokens_generated"]
        t0 = time.perf_counter()
        while True:
            busy = eng.step()
            now = time.perf_counter()
            waiting = []
            for ts, q in subs:
                if q.empty():
                    waiting.append((ts, q))
                else:
                    ttfts.append((now - ts) * 1e3)
            subs = waiting
            if not busy:
                break
        dt = time.perf_counter() - t0
        return (eng.metrics["tokens_generated"] - n0) / dt, ttfts

    t0 = time.perf_counter()
    eng.warmup()
    burst(4)   # admission/prefill program compiles
    note(f"  programs compiled in {time.perf_counter() - t0:.1f}s")
    base = sched_base(eng)
    d0 = eng.metrics["decode_dispatches"]
    s0 = eng.metrics["decode_steps_dispatched"]
    x0 = {k: v for k, v in eng.metrics.items()
          if k.startswith("rloop_exit_")}
    tput, ttfts = [], []
    for _ in range(args.windows):
        tps, tt = burst(args.decode_steps)
        tput.append(tps)
        ttfts.extend(tt)
    m = dict(eng.metrics)
    rows = getattr(eng, "_ragged_rows", 0)
    util = (m.get("ragged_tokens_packed", 0)
            / max(m.get("ragged_dispatches", 0) * rows, 1))
    import jax

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", dev.platform)
    ttfts.sort()
    return {
        "tok_s": st.median(tput),
        "ttft_p50_ms": ttfts[len(ttfts) // 2],
        "ttft_p95_ms": ttfts[min(len(ttfts) - 1, int(len(ttfts) * 0.95))],
        "budget_utilization": round(util, 4),
        # dispatch-boundary amortization over the measured windows only
        # (warmup/compile bursts excluded) — the fused leg's headline
        "steps_per_dispatch": round(
            (m["decode_steps_dispatched"] - s0)
            / max(m["decode_dispatches"] - d0, 1), 2),
        "loop_exit_reasons": {
            k[len("rloop_exit_"):]: int(v - x0.get(k, 0))
            for k, v in m.items() if k.startswith("rloop_exit_")
            and v - x0.get(k, 0) > 0},
        "sched": sched_stats(eng, base, toks_per_s=st.median(tput),
                             device_kind=kind),
        "metrics": m,
    }


def bench_ragged(args, size: str, on_cpu: bool):
    """Ragged continuous batching A/B (one process, same token work):

      dense mixed  : mixed-length stream, ragged off (bucketed prefill +
                     separate decode dispatches) — the ragged_over_dense
                     denominator,
      ragged mixed : the same stream through the flat-stream mixed
                     dispatch,
      ragged equal : equal-length stream, ragged on — the packing
                     reference; mixed-length serving must hold >= ~0.9x of
                     it, since the ragged pack never pads lengths,
      ragged-fused : the mixed stream again with the ISSUE 16 multi-step
                     device loop (`--ragged-loop-steps`, 0 disables the
                     leg) — reports steps/dispatch, the loop-exit reason
                     mix, and fused_over_ragged vs the single-step leg."""
    import jax

    from localai_tpu.engine.loader import load_config, load_params
    from localai_tpu.ops.paged import BLOCK

    tmp = tempfile.mkdtemp(prefix="bench-ckpt-")
    ckpt = write_synthetic_checkpoint(size, os.path.join(tmp, size))
    os.environ["LOCALAI_ALLOW_SYNTHETIC"] = "1"
    dtype = args.dtype or ("int8" if size == "8b" else "bfloat16")
    if on_cpu:
        dtype = args.dtype or "float32"
    cfg = load_config(ckpt, dtype=dtype)
    context = min(args.context, cfg.max_position)
    params = load_params(ckpt, cfg, dtype=dtype)
    jax.block_until_ready(params)
    note("params initialized")

    tokens = min(args.prompt_len * 3 // 2 + args.decode_steps + 33, context)
    pages = args.kv_pages or args.slots * (-(-tokens // BLOCK)) + 1
    budget = args.ragged_budget or args.slots * 8 + 128
    note(f"pool {pages} blocks, token budget {budget} rows")

    dense = _ragged_leg(args, cfg, params, context, pages, 0, mixed=True)
    note(f"dense mixed: {dense['tok_s']:.1f} tok/s, "
         f"ttft p50 {dense['ttft_p50_ms']:.0f}ms")
    ragged = _ragged_leg(args, cfg, params, context, pages, budget,
                         mixed=True)
    note(f"ragged mixed: {ragged['tok_s']:.1f} tok/s "
         f"({ragged['tok_s'] / max(dense['tok_s'], 1e-9):.2f}x dense), "
         f"ttft p50 {ragged['ttft_p50_ms']:.0f}ms, "
         f"budget util {ragged['budget_utilization']:.2f}")
    equal = _ragged_leg(args, cfg, params, context, pages, budget,
                        mixed=False)
    note(f"ragged equal: {equal['tok_s']:.1f} tok/s (mixed holds "
         f"{ragged['tok_s'] / max(equal['tok_s'], 1e-9):.2f}x of it)")
    fused = None
    if args.ragged_loop_steps > 1:
        fused = _ragged_leg(args, cfg, params, context, pages, budget,
                            mixed=True, loop_steps=args.ragged_loop_steps)
        note(f"ragged fused: {fused['tok_s']:.1f} tok/s "
             f"({fused['tok_s'] / max(ragged['tok_s'], 1e-9):.2f}x "
             f"single-step), {fused['steps_per_dispatch']:.1f} "
             f"steps/dispatch, ttft p50 {fused['ttft_p50_ms']:.0f}ms, "
             f"exits {fused['loop_exit_reasons']}")
    import shutil

    shutil.rmtree(tmp, ignore_errors=True)
    return dense, ragged, equal, fused, pages, budget, context, dtype


# ---------------------------------------------------------------- soup mode

SOUP_CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    '{"a": 12, "b": "hello world"} {"a": 7, "b": "tokens"}',
    "pack my box with five dozen liquor jugs",
    '[1, 2, 3] {"key": "value", "n": 42} true false null',
]

SOUP_SCHEMA = {"type": "object",
               "properties": {"a": {"type": "integer"},
                              "b": {"type": "string"}},
               "required": ["a", "b"]}


def _soup_checkpoint(size: str, path: str) -> str:
    """A synthetic checkpoint WITH a tokenizer: grammar compilation needs
    real token texts, so train a small byte-level BPE in-process (the
    `tokenizers` core dep — no torch) and clamp the config's vocab to it.
    Soup numbers are self-relative (constrained vs plain on the SAME
    geometry), so shrinking the vocab from the named size is fair."""
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, \
        trainers

    write_synthetic_checkpoint(size, path)
    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=min(SIZES[size]["vocab_size"], 512) - 2,
        special_tokens=["<s>", "</s>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
        show_progress=False)
    tok.train_from_iterator(SOUP_CORPUS * 4, trainer=trainer)
    tok.save(os.path.join(path, "tokenizer.json"))
    with open(os.path.join(path, "tokenizer_config.json"), "w") as fh:
        json.dump({"bos_token": "<s>", "eos_token": "</s>",
                   "add_bos_token": True}, fh)
    with open(os.path.join(path, "config.json")) as fh:
        body = json.load(fh)
    body["vocab_size"] = tok.get_vocab_size()
    with open(os.path.join(path, "config.json"), "w") as fh:
        json.dump(body, fh)
    return path


def bench_soup(args, size: str, on_cpu: bool):
    """--mode soup: ONE draft+ragged+paged engine serving a mixed tenant
    trace — grammar-constrained (device automaton tables), multimodal
    (packed embedding injects), and plain streams, all speculative (the
    engine drafts against itself). Two legs on the same warmed engine:

      plain : every tenant unconstrained — the denominator,
      soup  : tenants cycle plain / grammar / mm — the number the one-
              program claim moves: constrained_over_plain >= ~0.8 means
              constrained traffic rides the fast paths instead of dense
              per-token fallbacks.

    The measured soup windows run under the dispatch-budget tripwire and a
    compile-count snapshot; dense_fallback_dispatches and per-tenant path
    counts come from the engine's own accounting."""
    import statistics as st

    import jax
    import numpy as np

    from localai_tpu.engine import (
        Engine, EngineConfig, GenRequest, Tokenizer, load_config,
        load_params,
    )
    from localai_tpu.functions.grammars import json_schema_grammar
    from localai_tpu.ops.paged import BLOCK
    from localai_tpu.ops.sampling import SamplingParams
    from localai_tpu.testing.tripwires import (
        decode_compile_count, dispatch_budget,
    )

    tmp = tempfile.mkdtemp(prefix="bench-ckpt-")
    ckpt = _soup_checkpoint(size, os.path.join(tmp, size))
    os.environ["LOCALAI_ALLOW_SYNTHETIC"] = "1"
    dtype = args.dtype or ("float32" if on_cpu else "bfloat16")
    cfg = load_config(ckpt, dtype=dtype)
    context = min(args.context, cfg.max_position)
    params = load_params(ckpt, cfg, dtype=dtype)
    jax.block_until_ready(params)
    tok = Tokenizer.from_dir(ckpt)
    note("params + tokenizer ready")

    gamma = 3
    tokens = min(args.prompt_len * 3 // 2 + args.decode_steps + gamma + 34,
                 context)
    pages = args.kv_pages or \
        args.slots * (-(-tokens // BLOCK)) + args.slots + 1
    budget = args.ragged_budget or args.slots * (gamma + 1) + 128
    note(f"pool {pages} blocks, token budget {budget} rows, gamma {gamma}")

    eng = Engine(cfg, params, tok, EngineConfig(
        max_slots=args.slots, max_context=context,
        prefill_buckets=(128, min(512, context)),
        prefill_chunk=min(128, context),
        kv_pages=pages, prompt_cache=False, gamma=gamma,
        ragged_token_budget=budget), draft=(cfg, params))
    eng.record_paths = True
    grammar = json_schema_grammar(SOUP_SCHEMA)
    embed = np.asarray(params["embed"], np.float32)
    rng = np.random.default_rng(0)

    def make_req(kind):
        n = int(rng.integers(max(8, args.prompt_len // 2),
                             args.prompt_len * 3 // 2 + 1))
        ids = rng.integers(2, cfg.vocab_size, n).tolist()
        sp = SamplingParams(temperature=0.8, top_k=40,
                            seed=int(rng.integers(1 << 30)))
        r = GenRequest(ids, sp, max_tokens=args.decode_steps,
                       ignore_eos=(kind != "grammar"))
        if kind == "grammar":
            r.grammar = grammar
        elif kind == "mm":
            r.mm_embeds = embed[ids[1:5]] + 0.25
            r.mm_positions = np.arange(1, 5)
        return r

    def burst(kinds):
        # 2x oversubscription so freed slots backfill within the window
        reqs = [(k, eng.submit(make_req(k))) for k in kinds * 2]
        n0 = eng.metrics["tokens_generated"]
        t0 = time.perf_counter()
        while eng.step():
            pass
        dt = time.perf_counter() - t0
        for kind, (rid, _) in reqs:
            tenant_of[rid] = kind
        return (eng.metrics["tokens_generated"] - n0) / dt

    tenant_of: dict = {}
    plain_kinds = ["plain"] * args.slots
    soup_kinds = [("plain", "grammar", "mm")[i % 3]
                  for i in range(args.slots)]

    t0 = time.perf_counter()
    eng.warmup()
    burst(soup_kinds[: max(3, args.slots // 2)])  # program compiles
    note(f"  programs compiled in {time.perf_counter() - t0:.1f}s")
    warm_compiles = decode_compile_count(eng)
    tenant_of.clear()
    eng.req_path_counts.clear()

    plain_tps = [burst(plain_kinds) for _ in range(args.windows)]
    note(f"plain: {st.median(plain_tps):.1f} tok/s")
    d0 = eng.metrics["decode_dispatches"]
    r0 = eng.metrics["ragged_dispatches"]
    sbase = sched_base(eng)
    with dispatch_budget(eng):
        soup_tps = [burst(soup_kinds) for _ in range(args.windows)]
    note(f"soup : {st.median(soup_tps):.1f} tok/s "
         f"({st.median(soup_tps) / max(st.median(plain_tps), 1e-9):.2f}x "
         f"plain)")

    per_tenant: dict = {}
    for rid, kind in tenant_of.items():
        agg = per_tenant.setdefault(kind, {})
        for path, cnt in eng.req_path_counts.get(rid, {}).items():
            agg[path] = agg.get(path, 0) + cnt
    dense_fallback = (eng.metrics["decode_dispatches"] - d0) \
        - (eng.metrics["ragged_dispatches"] - r0)
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", dev.platform)
    sstats = sched_stats(eng, sbase, toks_per_s=st.median(soup_tps),
                         device_kind=kind)
    # every dense (non-ragged) dispatch in the soup windows emits exactly
    # one dispatch-category reason code, so these sum to dense_fallback
    from localai_tpu.telemetry import DISPATCH_CODES

    fallback_reasons = {c: n for c, n in
                        (sstats.get("reason_codes") or {}).items()
                        if c in DISPATCH_CODES}
    result = {
        "tok_s": st.median(soup_tps),
        "plain_tok_s": st.median(plain_tps),
        "per_tenant_paths": per_tenant,
        "dense_fallback_dispatches": int(dense_fallback),
        "dense_fallback_reasons": fallback_reasons,
        "sched": sstats,
        "compile_count_delta": decode_compile_count(eng) - warm_compiles,
        "grammar_table_states": int(
            eng.metrics.get("grammar_table_states", 0)),
        "draft_acceptance": round(
            eng.metrics.get("draft_accepted", 0)
            / max(eng.metrics.get("draft_proposed", 1), 1), 4),
        "metrics": dict(eng.metrics),
    }
    import shutil

    shutil.rmtree(tmp, ignore_errors=True)
    return result, pages, budget, context, dtype, gamma


def _longctx_leg(args, cfg, params, *, max_context, kv_policy="",
                 kv_cold_pages=0, prompt_tokens, decode_steps,
                 greedy=False, seed=1):
    """One single-slot long-context leg: admit a `prompt_tokens` prompt,
    wait until prefill completes, then time the pure decode window.
    Returns (tok_s, token_ids, metrics)."""
    import numpy as np

    from localai_tpu.engine import Engine, EngineConfig, GenRequest
    from localai_tpu.engine.kvtier import (
        engine_margin_tokens, parse_policy, resident_blocks,
    )
    from localai_tpu.ops.paged import blocks_needed
    from localai_tpu.ops.sampling import SamplingParams

    chunk = min(512, max_context)
    ec = EngineConfig(max_slots=1, max_context=max_context,
                      prefill_buckets=(128, chunk), prefill_chunk=chunk,
                      kv_pages=1, kv_policy=kv_policy,
                      kv_cold_pages=kv_cold_pages)
    pol = parse_policy(kv_policy)
    if pol.windowed:
        pages = resident_blocks(pol, engine_margin_tokens(ec)) + 3
    else:
        pages = blocks_needed(max_context) + 2
    ec = EngineConfig(max_slots=1, max_context=max_context,
                      prefill_buckets=(128, chunk), prefill_chunk=chunk,
                      kv_pages=pages, kv_policy=kv_policy,
                      kv_cold_pages=kv_cold_pages)
    eng = Engine(cfg, params, None, ec)
    rng = np.random.default_rng(seed)

    def req(n_prompt, n_decode):
        return GenRequest(
            prompt_ids=rng.integers(1, cfg.vocab_size, n_prompt).tolist(),
            params=SamplingParams(temperature=0.0 if greedy else 0.8,
                                  seed=seed),
            max_tokens=n_decode, ignore_eos=True)

    # compile admission + decode on a short request so the timed window
    # below measures steady-state decode, not XLA compiles
    _, out = eng.submit(req(8, 4))
    while eng.step():
        pass
    while not out.empty():
        out.get()

    rng = np.random.default_rng(seed)   # same prompt across legs
    _, out = eng.submit(req(prompt_tokens, decode_steps))
    while eng._slots[0] is None or not eng._slots[0].prefilled:
        eng.step()
    n0 = eng.metrics["tokens_generated"]
    t0 = time.perf_counter()
    while eng.step():
        pass
    dt = time.perf_counter() - t0
    toks = eng.metrics["tokens_generated"] - n0
    ids = []
    while not out.empty():
        o = out.get()
        if o.token_id >= 0:
            ids.append(o.token_id)
    return toks / max(dt, 1e-9), ids, dict(eng.metrics)


def bench_longctx(args, size: str, on_cpu: bool):
    """Long-context KV tier A/B (BASELINE #2f, engine/kvtier.py): decode
    tok/s at ctx long_tokens under sink_window vs ctx-1k under full KV
    (same geometry, one process), plus the tier's two documented parity
    regimes — token-exact when sinks+window cover the whole context, and
    int8-tolerance agreement for quantize_cold (full-precision sinks +
    window, sub-channel-int8 middle)."""
    import jax

    from localai_tpu.engine.loader import load_config, load_params
    from localai_tpu.ops.paged import BLOCK, blocks_needed

    long_tokens = args.longctx_tokens
    sinks, window = args.kv_sinks, args.kv_window
    decode = args.decode_steps
    tmp = tempfile.mkdtemp(prefix="bench-ckpt-")
    ckpt = write_synthetic_checkpoint(size, os.path.join(tmp, size))
    # the tier exists to serve contexts past the model's native training
    # length — raise the synthetic geometry's rope table to match
    cfgp = os.path.join(ckpt, "config.json")
    with open(cfgp) as fh:
        body = json.load(fh)
    body["max_position_embeddings"] = max(
        body.get("max_position_embeddings", 0),
        long_tokens + decode + 2 * BLOCK)
    with open(cfgp, "w") as fh:
        json.dump(body, fh)
    os.environ["LOCALAI_ALLOW_SYNTHETIC"] = "1"
    dtype = args.dtype or ("int8" if size == "8b" else "bfloat16")
    if on_cpu:
        dtype = args.dtype or "float32"
    cfg = load_config(ckpt, dtype=dtype)
    params = load_params(ckpt, cfg, dtype=dtype)
    jax.block_until_ready(params)
    note("params initialized")

    policy = f"sink_window(sinks={sinks}, window={window})"
    short_ctx = 1024 + decode + 2 * BLOCK
    short_tok_s, _, _ = _longctx_leg(
        args, cfg, params, max_context=short_ctx, prompt_tokens=1024,
        decode_steps=decode)
    note(f"ctx-1k full: {short_tok_s:.1f} tok/s")
    long_ctx = long_tokens + decode + 2 * BLOCK
    long_tok_s, _, lm = _longctx_leg(
        args, cfg, params, max_context=long_ctx, kv_policy=policy,
        prompt_tokens=long_tokens, decode_steps=decode)
    note(f"ctx-{long_tokens // 1024}k {policy}: {long_tok_s:.1f} tok/s "
         f"({long_tok_s / max(short_tok_s, 1e-9):.2f}x of ctx-1k), "
         f"pool peak {lm['kv_blocks_peak']} blocks, "
         f"{lm['kv_evictions']} evictions")

    # parity probe 1: sinks+window >= context -> nothing ever leaves
    # retention, token streams must be EXACTLY the full-KV ones
    probe_ctx = 512 + 2 * BLOCK
    _, ref_ids, _ = _longctx_leg(
        args, cfg, params, max_context=probe_ctx, prompt_tokens=384,
        decode_steps=32, greedy=True)
    _, tier_ids, _ = _longctx_leg(
        args, cfg, params, max_context=probe_ctx,
        kv_policy="sink_window(sinks=128, window=640)", prompt_tokens=384,
        decode_steps=32, greedy=True)
    parity_exact = tier_ids == ref_ids
    note(f"parity (sinks+window >= ctx): "
         f"{'exact' if parity_exact else 'DIVERGED'}")

    # parity probe 2: quantize_cold with window < prompt — every position
    # stays readable (middle blocks at int8), so agreement vs full KV is
    # bounded by int8 quantization error only (the documented tolerance)
    cold_ctx = 1024 + 2 * BLOCK
    _, ref2, _ = _longctx_leg(
        args, cfg, params, max_context=cold_ctx, prompt_tokens=768,
        decode_steps=32, greedy=True)
    _, cold_ids, cm = _longctx_leg(
        args, cfg, params, max_context=cold_ctx,
        kv_policy="sink_window(sinks=128, window=256, quantize_cold=true)",
        kv_cold_pages=blocks_needed(cold_ctx) + 2, prompt_tokens=768,
        decode_steps=32, greedy=True)
    agree = sum(a == b for a, b in zip(cold_ids, ref2))
    cold_agreement = agree / max(len(ref2), 1)
    note(f"parity (quantize_cold int8): {cold_agreement:.2f} agreement, "
         f"{cm['kv_cold_blocks']} blocks demoted")

    import shutil

    shutil.rmtree(tmp, ignore_errors=True)
    return {
        "short_tok_s": short_tok_s, "long_tok_s": long_tok_s,
        "long_tokens": long_tokens, "policy": policy,
        "kv_blocks_peak": lm["kv_blocks_peak"],
        "kv_evictions": lm["kv_evictions"],
        "parity_exact": parity_exact,
        "parity_cold_agreement": cold_agreement,
        "cold_blocks": cm["kv_cold_blocks"],
        "dtype": dtype,
    }


def bench_embed(args, size: str, on_cpu: bool):
    """BASELINE config #3: /v1/embeddings-path throughput (served gRPC
    Embedding RPC, batch inputs) → embeddings/s."""
    import numpy as np

    from localai_tpu.config import AppConfig, ModelConfig
    from localai_tpu.core.manager import ModelManager

    tmp = tempfile.mkdtemp(prefix="bench-ckpt-")
    ckpt = write_synthetic_checkpoint(size, os.path.join(tmp, size))
    # batched embeddings tokenize server-side: give the synthetic checkpoint
    # an instant WordLevel tokenizer ("<n>" → id n, whitespace-split)
    from tokenizers import Tokenizer, models, pre_tokenizers

    vocab = SIZES[size]["vocab_size"]
    tok = Tokenizer(models.WordLevel(
        {str(i): i for i in range(min(vocab, 1000))}, unk_token="0"))
    tok.pre_tokenizer = pre_tokenizers.WhitespaceSplit()
    tok.save(os.path.join(ckpt, "tokenizer.json"))
    with open(os.path.join(ckpt, "tokenizer_config.json"), "w") as fh:
        json.dump({"bos_token": None, "eos_token": None,
                   "add_bos_token": False}, fh)
    os.environ["LOCALAI_ALLOW_SYNTHETIC"] = "1"
    os.environ["LOCALAI_NO_PREWARM"] = "1"   # embed RPC needs no decode warm
    dtype = args.dtype or ("float32" if on_cpu else "bfloat16")
    if on_cpu:
        os.environ["LOCALAI_JAX_PLATFORM"] = "cpu"
    mcfg = ModelConfig.from_dict({
        "name": f"bench-{size}", "backend": "llm", "context_size": 512,
        "parallel": 2, "dtype": dtype, "embeddings": True,
        "prefill_buckets": [128], "parameters": {"model": ckpt},
    })
    manager = ModelManager(AppConfig(models_path=tmp))
    handle = manager.load(mcfg)
    rng = np.random.default_rng(0)
    batch = [" ".join(str(t) for t in rng.integers(1, min(vocab, 999), 24))
             for _ in range(args.embed_batch)]
    try:
        handle.client.embedding(prompts=batch)      # warmup (compile)
        rates = []
        for _ in range(args.windows):
            t0 = time.perf_counter()
            r = handle.client.embedding(prompts=batch)
            dt = time.perf_counter() - t0
            n = len(r.vectors) or len(batch)
            rates.append(n / dt)
            note(f"embed window: {rates[-1]:.1f} embeddings/s ({n} x 24 tok)")
    finally:
        # never leak the accelerator-holding backend into later ladder
        # stages, and never leave checkpoints accumulating in /tmp
        import shutil

        manager.stop_all()
        shutil.rmtree(tmp, ignore_errors=True)
    return statistics.median(rates)


def bench_whisper(args, on_cpu: bool):
    """BASELINE config #4: /v1/audio/transcriptions real-time factor
    (audio-seconds transcribed per wall-second) through the whisper backend."""
    import numpy as np
    import torch
    from transformers import WhisperConfig, WhisperForConditionalGeneration

    from localai_tpu.config import AppConfig, ModelConfig
    from localai_tpu.core.manager import ModelManager

    if on_cpu:
        os.environ["LOCALAI_JAX_PLATFORM"] = "cpu"
    tmp = tempfile.mkdtemp(prefix="bench-whisper-")
    torch.manual_seed(0)
    if on_cpu:
        # CPU smoke: tiny geometry + short clip (whisper-base on CPU f32
        # takes minutes per window — harness validation only)
        wcfg = WhisperConfig(
            vocab_size=51865, d_model=64, encoder_layers=2,
            decoder_layers=2, encoder_attention_heads=4,
            decoder_attention_heads=4, encoder_ffn_dim=128,
            decoder_ffn_dim=128, num_mel_bins=80,
            max_source_positions=1500, max_target_positions=64)
    else:
        # whisper-base geometry (the BASELINE config names whisper-base)
        wcfg = WhisperConfig(
            vocab_size=51865, d_model=512, encoder_layers=6,
            decoder_layers=6, encoder_attention_heads=8,
            decoder_attention_heads=8, encoder_ffn_dim=2048,
            decoder_ffn_dim=2048, num_mel_bins=80,
            max_source_positions=1500, max_target_positions=448)
    m = WhisperForConditionalGeneration(wcfg)
    m.generation_config.forced_decoder_ids = None
    m.generation_config.suppress_tokens = None
    m.generation_config.begin_suppress_tokens = None
    m.save_pretrained(tmp, safe_serialization=True)
    mcfg = ModelConfig.from_dict({
        "name": "bench-whisper", "backend": "whisper",
        "parameters": {"model": tmp},
    })
    manager = ModelManager(AppConfig(models_path=tmp))
    handle = manager.load(mcfg)
    secs = 5.0 if on_cpu else 20.0
    sr = 16000
    t = np.arange(int(secs * sr)) / sr
    pcm = (0.1 * np.sin(2 * np.pi * 220 * t)).astype(np.float32)
    import struct
    import wave

    wav = os.path.join(tmp, "in.wav")
    with wave.open(wav, "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(sr)
        w.writeframes(
            struct.pack(f"<{len(pcm)}h",
                        *(np.clip(pcm, -1, 1) * 32767).astype(np.int16)))
    try:
        handle.client.transcribe(dst=wav, language="en")     # warmup
        rtfs = []
        for _ in range(args.windows):
            t0 = time.perf_counter()
            handle.client.transcribe(dst=wav, language="en")
            rtfs.append(secs / (time.perf_counter() - t0))
            note(f"whisper window: RTF {rtfs[-1]:.2f}x")
    finally:
        import shutil

        manager.stop_all()
        shutil.rmtree(tmp, ignore_errors=True)
    return statistics.median(rtfs)


def bench_session(args, size: str, on_cpu: bool) -> dict:
    """--mode session (ISSUE 17): multi-turn conversations through the host
    KV tier. One in-process engine serves turn 1 of a long conversation,
    other tenants churn its device pool (the retained prefix spills to the
    host tier), then turn 2 arrives — TTFT with host re-admission vs the
    re-prefill baseline vs the warm device-cache hit, plus a worker-restart
    leg (a FRESH engine adopting the survivor HostKVPool) and a greedy
    parity check through the re-admitted int8 blocks.

    ISSUE 19 adds a preempt/resume leg: a mid-decode spill-drain freezes a
    live generation into a ResumeToken, and TTFT-to-next-token resuming on
    a fresh engine that adopts the survivor pool is measured against the
    same token resumed by re-prefilling from scratch (resume_speedup)."""
    import jax
    import numpy as np

    from localai_tpu.engine import Engine, EngineConfig, GenRequest
    from localai_tpu.engine.loader import load_config, load_params
    from localai_tpu.ops.paged import blocks_needed
    from localai_tpu.ops.sampling import SamplingParams

    tmp = tempfile.mkdtemp(prefix="bench-ckpt-")
    ckpt = write_synthetic_checkpoint(size, os.path.join(tmp, size))
    os.environ["LOCALAI_ALLOW_SYNTHETIC"] = "1"
    dtype = args.dtype or ("int8" if size == "8b" else "bfloat16")
    if on_cpu:
        dtype = args.dtype or "float32"
    cfg = load_config(ckpt, dtype=dtype)
    S = min(args.session_tokens, cfg.max_position - 192)
    context = S + 192
    params = load_params(ckpt, cfg, dtype=dtype)
    jax.block_until_ready(params)
    note(f"params initialized ({S}-token conversations, ctx {context})")

    # pool sized just above one conversation's footprint so the churn
    # tenants force the released turn-1 chain out of the device pool (the
    # host tier is then its only home); the int8 hot cache makes the
    # spill→readmit round trip byte-exact
    pages = blocks_needed(context) + 1
    budget = args.kv_host_bytes or (1 << 30)

    def mk(kv_host_bytes=0, kvhost=None):
        return Engine(cfg, params, None, EngineConfig(
            max_slots=2, max_context=context,
            prefill_buckets=(128, min(512, context)),
            prefill_chunk=min(512, context),
            cache_type="int8", kv_pages=pages, prompt_cache=True,
            kv_host_bytes=kv_host_bytes), kvhost=kvhost)

    rng = np.random.default_rng(0)
    turn1_ids = rng.integers(1, cfg.vocab_size, S).tolist()
    follow_ids = rng.integers(1, cfg.vocab_size, 64).tolist()

    def greq(ids, n=16):
        return GenRequest(prompt_ids=list(ids), max_tokens=n,
                          params=SamplingParams(temperature=0.0),
                          ignore_eos=True)

    def run_turn(eng, ids, n=16):
        """(ttft_ms, generated token ids) — greedy, fully drained."""
        rid, out = eng.submit(greq(ids, n))
        t0 = time.perf_counter()
        ttft = None
        toks = []
        while True:
            eng.step()
            while not out.empty():
                so = out.get()
                if ttft is None:
                    ttft = (time.perf_counter() - t0) * 1e3
                if so.token_id >= 0:
                    toks.append(so.token_id)
                if so.finished:
                    while eng.step():
                        pass
                    return ttft, toks

    def churn(eng, seeds=(11, 12, 13)):
        """Distinct same-length tenants: reclaims the released turn-1
        chain (host spill on a tiered engine, plain death otherwise)."""
        for s in seeds:
            r = np.random.default_rng(s)
            run_turn(eng, r.integers(1, cfg.vocab_size, S).tolist(), n=4)

    def prewarm(eng, with_host: bool):
        """Compile every program a measured leg will hit: chunked prefill,
        decode, the shared-prefix resume path (prefix hit + suffix-only
        prefill), and (host legs) the spill + readmit programs."""
        w = np.random.default_rng(99).integers(1, cfg.vocab_size, S).tolist()
        ext = np.random.default_rng(97).integers(
            1, cfg.vocab_size, 80).tolist()
        run_turn(eng, w, n=4)
        if with_host:
            churn(eng, seeds=(98, 96))    # spill compile + evict w's chain
        run_turn(eng, w + ext, n=4)       # resume (+ readmit) compile
        if eng._kvhost is not None:
            eng._host_drain()             # settle pending spill fetches

    # -- baseline engine: warm device hit, then the re-prefill floor ------
    note("baseline leg (no host tier)...")
    ebase = mk(0)
    prewarm(ebase, with_host=False)
    ttft1_base, gen1 = run_turn(ebase, turn1_ids)
    conv = turn1_ids + gen1 + follow_ids
    ttft2_warm, out_warm = run_turn(ebase, conv)      # device prefix hit
    churn(ebase)
    ttft2_reprefill, out_reprefill = run_turn(ebase, conv)
    note(f"baseline: warm {ttft2_warm:.1f} ms, "
         f"re-prefill {ttft2_reprefill:.1f} ms")

    # -- host-tier engine: churn spills, turn 2 re-admits -----------------
    note(f"host-tier leg (budget {budget / 1e6:.0f} MB)...")
    ehost = mk(budget)
    prewarm(ehost, with_host=True)
    ttft1, gen1h = run_turn(ehost, turn1_ids)
    assert gen1h == gen1, "turn-1 greedy streams diverged across engines"
    churn(ehost)
    ehost._host_drain()   # spill cost lands on churn time, not turn-2 TTFT
    hits0 = ehost.metrics["kv_host_hits"]
    ttft2_host, out_host = run_turn(ehost, conv)
    ehost._host_drain()
    m = dict(ehost.metrics)
    readmitted = int(m["kv_host_hits"] - hits0)
    note(f"host tier: turn2 {ttft2_host:.1f} ms, {readmitted} blocks "
         f"re-admitted, pool peak {m['kv_host_bytes_peak'] / 1e6:.1f} MB")

    # -- worker restart: fresh engine adopts the survivor pool ------------
    note("restart leg (fresh engine, adopted host pool)...")
    erest = mk(0, kvhost=ehost._kvhost)
    prewarm(erest, with_host=True)
    hits0r = erest.metrics["kv_host_hits"]
    ttft2_restart, out_restart = run_turn(erest, conv)
    rm = dict(erest.metrics)

    # -- preempt/resume leg (ISSUE 19): TTFT-to-next-token after a --------
    # mid-decode spill-drain, resumed on a FRESH engine adopting the
    # survivor pool, vs the same ResumeToken re-prefilled from scratch
    note("preempt/resume leg (spill-drain vs re-prefill)...")
    from localai_tpu.engine.resume import ResumeToken

    def mkp(kv_host_bytes=0, kvhost=None, loop=8, block=4):
        # short fused bursts on the preempting engine so the preempt lands
        # mid-generation instead of after one whole-turn dispatch; the
        # resume engines run one step per dispatch (loop=1, block=1) so
        # TTFT observes the true first post-resume token — readmit vs
        # re-prefill — instead of a shared whole-burst constant (greedy
        # parity across dispatch groupings is the tests/test_decode_loop
        # guarantee). BLOCK-sized prefill chunks: a re-prefill walks the
        # whole conversation one chunk dispatch at a time while a
        # survivor-pool resume pays a single sub-block suffix chunk — the
        # dispatch asymmetry the checkpoint is buying
        return Engine(cfg, params, None, EngineConfig(
            max_slots=2, max_context=context,
            prefill_buckets=(128,), prefill_chunk=128,
            cache_type="int8", kv_pages=pages, prompt_cache=True,
            decode_loop=loop, decode_block=block,
            kv_host_bytes=kv_host_bytes), kvhost=kvhost)

    def run_resume(eng, tok, n):
        """(ttft_ms to the first post-resume token, continuation ids)."""
        rid, out = eng.submit(GenRequest(
            prompt_ids=tok.resume_prompt, max_tokens=n,
            params=SamplingParams(temperature=0.0), ignore_eos=True,
            resume=tok.payload()))
        t0 = time.perf_counter()
        ttft = None
        toks = []
        while True:
            eng.step()
            while not out.empty():
                so = out.get()
                if ttft is None:
                    ttft = (time.perf_counter() - t0) * 1e3
                if so.token_id >= 0:
                    toks.append(so.token_id)
                if so.finished:
                    while eng.step():
                        pass
                    return ttft, toks

    def run_until(eng, ids, n, k):
        """Step until >= k tokens observed, then spill-drain preempt."""
        rid, out = eng.submit(greq(ids, n))
        toks = []
        while len(toks) < k:
            eng.step()
            while not out.empty():
                so = out.get()
                if so.token_id >= 0:
                    toks.append(so.token_id)
                assert not so.finished, "finished before the preempt landed"
        man = eng.preempt()
        while not out.empty():
            so = out.get()
            if so.token_id >= 0:
                toks.append(so.token_id)
        return toks, man

    NPRE = 32
    # uninterrupted reference on its own engine: each preempted run must
    # be a FRESH prefill so the slot owns its whole chain — a prefix hit
    # on a retained reference chain would leave most blocks shared
    # (unspilled) and the resume would re-prefill them anyway
    eref = mkp(0)
    prewarm(eref, with_host=False)
    epre = mkp(budget)
    prewarm(epre, with_host=True)
    eres = mkp(0, kvhost=epre._kvhost, loop=1, block=1)
    prewarm(eres, with_host=True)
    erep = mkp(0, loop=1, block=1)
    prewarm(erep, with_host=False)

    # median of 3 preempt->resume rounds, a fresh prompt each round so
    # every resume is a true survivor-pool readmit and every floor run a
    # true re-prefill (single-shot TTFTs at smoke scale are noise-bound)
    res_ms, rep_ms = [], []
    parity_res = parity_rep = True
    got_pre = []
    for rnd in range(3):
        ids = np.random.default_rng(200 + rnd).integers(
            1, cfg.vocab_size, S).tolist()
        _, ref_pre = run_turn(eref, ids, n=NPRE)
        got_pre, man = run_until(epre, ids, NPRE, 8)
        assert man, "preempt produced no resume manifest"
        assert len(got_pre) < NPRE, "preempt landed after the stream ended"
        tok = ResumeToken.from_dict(man[0])
        nrem = NPRE - tok.generated
        t_res, rest_res = run_resume(eres, tok, nrem)
        t_rep, rest_rep = run_resume(erep, tok, nrem)
        res_ms.append(t_res)
        rep_ms.append(t_rep)
        parity_res = parity_res and (got_pre + rest_res == ref_pre)
        parity_rep = parity_rep and (got_pre + rest_rep == ref_pre)
    ttft_resume = statistics.median(res_ms)
    ttft_reprefill = statistics.median(rep_ms)
    pm = dict(epre.metrics)
    note(f"preempt at {len(got_pre)} toks: resume {ttft_resume:.1f} ms "
         f"(readmit) vs {ttft_reprefill:.1f} ms (re-prefill)")
    resm, repm = dict(eres.metrics), dict(erep.metrics)

    for e in (ebase, ehost, erest, eref, epre, eres, erep):
        e.stop()
    import shutil

    shutil.rmtree(tmp, ignore_errors=True)
    return {
        "dtype": dtype, "session_tokens": S, "context": context,
        "kv_pages": pages, "budget_bytes": budget,
        "ttft1_ms": ttft1, "ttft1_base_ms": ttft1_base,
        "ttft2_warm_ms": ttft2_warm,
        "ttft2_reprefill_ms": ttft2_reprefill,
        "ttft2_host_ms": ttft2_host,
        "ttft2_restart_ms": ttft2_restart,
        "readmitted_blocks": readmitted,
        "restart_readmitted_blocks": int(rm.get("kv_host_hits", 0) - hits0r),
        # greedy parity vs the WARM device hit: spill→readmit on the int8
        # pool is byte-exact, so the host path must reproduce the retained-
        # on-device stream bit for bit. Re-prefill parity is informational
        # only — fresh prefill reads no quantized prefix KV while any
        # cache-resume path (device OR host) does, a pre-existing prefix-
        # cache asymmetry this tier inherits rather than introduces.
        "parity_host": out_host == out_warm,
        "parity_restart": out_restart == out_warm,
        "parity_reprefill": out_reprefill == out_warm,
        "kv_host_bytes_peak": int(m["kv_host_bytes_peak"]),
        "kv_host_spills": int(m["kv_host_spills"]),
        "kv_host_evictions": int(m["kv_host_evictions"]),
        # preempt/resume leg (ISSUE 19); block/readmit counts are
        # cumulative over the 3 measured rounds
        "ttft_resume_ms": ttft_resume,
        "ttft_resume_reprefill_ms": ttft_reprefill,
        "preempt_tokens": len(got_pre),
        "preempt_spilled_blocks": int(pm["preempt_spilled_blocks"]),
        "parity_resume": parity_res,
        "parity_resume_reprefill": parity_rep,
        "resume_readmits": int(resm["resume_readmits"]),
        "resume_reprefills": int(repm["resume_reprefills"]),
    }


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser()
    p.add_argument("--size", default=None,
                   help="tiny|1b|3b|8b (default: 8b on TPU, tiny on CPU)")
    p.add_argument("--mode", default="serve",
                   choices=["serve", "engine", "embed", "whisper", "paged",
                            "tp", "ragged", "longctx", "soup", "session"],
                   help="serve = gRPC backend subprocess (default); engine = "
                        "in-process; paged = dense AND paged in one process "
                        "with a paged_over_dense ratio; tp = single device "
                        "AND an N-device tensor-parallel mesh in one process "
                        "with a tp_over_single ratio (CPU: virtual 4-device "
                        "mesh); ragged = mixed-length continuous batching "
                        "through the flat-stream dispatch, three legs "
                        "(dense mixed / ragged mixed / ragged equal) with "
                        "ragged_over_dense + mixed_over_equal ratios; "
                        "longctx = KV lifecycle tier: ctx-32k decode under "
                        "sink_window vs ctx-1k full KV with a "
                        "longctx_over_short ratio, bounded-pool peak, and "
                        "token-parity probes (BASELINE #2f); "
                        "soup = mixed tenant trace (grammar + multimodal + "
                        "speculative + plain) on ONE draft+ragged engine "
                        "with a constrained_over_plain ratio, per-tenant "
                        "dispatch-path counts, and a dense-fallback count "
                        "(ISSUE 12); "
                        "session = multi-turn conversations through the "
                        "host KV tier: turn-2 TTFT with host re-admission "
                        "vs re-prefill vs warm device hit, a worker-restart "
                        "leg, and a greedy-parity check, with "
                        "turn2_over_turn1_ttft + readmit_speedup ratios "
                        "(ISSUE 17); "
                        "embed/whisper = BASELINE configs #3/#4")
    p.add_argument("--embed-batch", type=int, default=256)
    p.add_argument("--dtype", default=None,
                   help="override weights dtype (default: int8 for 8b, else bf16)")
    p.add_argument("--cpu", action="store_true", help="force CPU (local smoke)")
    p.add_argument("--slots", type=int, default=None,
                   help="concurrent streams; default 16 on the int8-KV "
                        "geometries (8b), 8 on dense-KV ones")
    p.add_argument("--prompt-len", type=int, default=120)
    p.add_argument("--decode-steps", type=int, default=128)
    p.add_argument("--windows", type=int, default=5)
    p.add_argument("--context", type=int, default=1024)
    p.add_argument("--decode-loop", type=int, default=None,
                   help="max steps per single-dispatch while-loop decode "
                        "block (engine mode; default: engine's 64; 0 "
                        "disables the loop — scan-ladder comparison runs)")
    p.add_argument("--ragged-budget", type=int, default=0,
                   help="ragged token rows per mixed dispatch (--mode "
                        "ragged; 0 = auto: slots*8 + 128 — every decode "
                        "slot plus one 128-token prefill chunk)")
    p.add_argument("--ragged-loop-steps", type=int, default=16,
                   help="max decode iterations per fused ragged dispatch "
                        "(--mode ragged's ragged-fused leg; 0/1 disables "
                        "the leg — single-step dispatch only)")
    p.add_argument("--longctx-tokens", type=int, default=32768,
                   help="long-leg prompt length for --mode longctx")
    p.add_argument("--kv-window", type=int, default=1024,
                   help="sink_window retention window for --mode longctx")
    p.add_argument("--kv-sinks", type=int, default=256,
                   help="attention-sink tokens for --mode longctx")
    p.add_argument("--session-tokens", type=int, default=4096,
                   help="tokens per conversation turn-1 prefix for --mode "
                        "session (the amount the host tier must carry "
                        "across device-pool eviction)")
    p.add_argument("--kv-host-bytes", type=int, default=0,
                   help="host-RAM KV tier budget for --mode session "
                        "(0 = auto 1 GiB); the spill tier catching blocks "
                        "the device pool evicts")
    p.add_argument("--kv-pages", type=int, default=0,
                   help="paged KV pool size in 128-token blocks "
                        "(0 = dense per-slot cache); lets slot count "
                        "oversubscribe context at ctx 8192")
    p.add_argument("--tensor-parallel", type=int, default=0,
                   help="shard the model over N devices (mesh data=1, "
                        "model=N; int8 weights shard too). 0 = single "
                        "device. --mode tp runs both legs and defaults N "
                        "to the largest axis the geometry divides into")
    p.add_argument("--trace", action="store_true",
                   help="telemetry run: record spans + fenced stage timings "
                        "(LOCALAI_TRACE/LOCALAI_PROFILE), write a "
                        "Chrome-trace dump and add a per-stage breakdown "
                        "to the result JSON")
    p.add_argument("--trace-out", default="bench_trace.json",
                   help="Chrome-trace output path for --trace")
    p.add_argument("--runs-dir", default=None,
                   help="artifact archive dir (default bench_runs/ next to "
                        "bench.py, or $BENCH_RUNS_DIR)")
    p.add_argument("--allow-cpu-fallback", action="store_true",
                   help="emit the CPU smoke number even when an archived "
                        "on-chip artifact exists (default: surface the "
                        "stale TPU artifact instead)")
    p.add_argument("--probe-timeout", type=int, default=0,
                   help="accelerator probe budget in seconds (0 = "
                        "$BENCH_PROBE_TIMEOUT_S or 900); split into an "
                        "escalating attempt ladder unless "
                        "--probe-single-attempt")
    p.add_argument("--probe-single-attempt", action="store_true",
                   help="one probe attempt spanning the whole timeout "
                        "budget — for a legitimately slow cold init the "
                        "ladder would kill mid-compile")
    p.add_argument("--probe-compile-cache", default="",
                   help="persistent XLA compilation cache dir "
                        "(JAX_COMPILATION_CACHE_DIR) for the probe child "
                        "AND the benched process — a warm cache turns a "
                        "multi-minute first_compile phase into seconds")
    p.add_argument("--probe-keepalive", action="store_true",
                   help="keep ONE probe child (with its initialized "
                        "device client) alive across modes in this "
                        "process: later probes PING it instead of "
                        "re-paying cold init")
    return p


def emit_result(result: dict, args) -> int:
    """Final scoreboard emission: fold in the --trace stage breakdown, the
    probe phase report, and the engine-histogram SLO fields; write the
    Chrome-trace dump, archive on-chip artifacts, print the JSON line."""
    report = getattr(args, "probe_report", None)
    if report is not None:
        result.setdefault("probe_report", report)
    # engine-sourced latency percentiles: serve mode captured the backend's
    # hist_* GetMetrics keys; in-process modes read the live registry.
    # setdefault — modes publishing their own under-load stopwatch numbers
    # (ragged) keep them.
    src = getattr(args, "slo_metrics", None)
    if src is None:
        try:
            from localai_tpu import telemetry

            slo = telemetry.maybe_slo()
            src = slo.flat() if slo is not None else {}
        except Exception:
            src = {}
    for k, v in slo_stats(src).items():
        result.setdefault(k, v)
    payload = getattr(args, "trace_payload", None)
    if payload is not None:
        profile = payload.get("profile") or {}
        stages = profile.get("stages") or {}
        if stages:
            result["stages"] = {
                name: dict(
                    share=round(st["share"], 4),
                    total_ms=round(st["total_ms"], 2),
                    p50_ms=round(st["p50_ms"], 3),
                    count=st["count"],
                    tok_s=round(st["tok_s"], 1),
                    **({"mfu": round(st["mfu"], 4)}
                       if st.get("mfu") else {}))
                for name, st in stages.items()}
            result["stage_coverage"] = round(profile.get("coverage", 0.0), 4)
        try:
            from localai_tpu import telemetry

            # backend spans + this (parent) process's rpc/client spans
            events = list(payload.get("spans") or [])
            events += telemetry.chrome_events()
            events.sort(key=lambda e: e.get("ts", 0))
            names = {os.getpid(): "bench"}
            if payload.get("pid"):
                names[payload["pid"]] = "backend"
            with open(args.trace_out, "w") as fh:
                json.dump(telemetry.chrome_trace(events, names), fh)
            note(f"chrome trace ({len(events)} events) -> {args.trace_out}")
        except Exception as e:
            note(f"trace dump failed: {e}")
    save_artifact(result, args.runs_dir or "")
    print(json.dumps(result))
    return 0


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.probe_compile_cache:
        # the benched process (backend subprocess or in-process jax) shares
        # the probe's persistent compilation cache
        os.environ["JAX_COMPILATION_CACHE_DIR"] = args.probe_compile_cache
    if args.trace:
        # env, not in-process flags: serve mode's backend subprocess must
        # inherit them (manager spawn copies os.environ)
        os.environ["LOCALAI_TRACE"] = "1"
        os.environ["LOCALAI_PROFILE"] = "1"

    on_cpu, probe_error, device_kind = probe_accelerator(args)
    if on_cpu and not args.cpu and not args.allow_cpu_fallback:
        # TPU expected but unreachable: the scoreboard gets the newest
        # archived on-chip artifact (flagged stale), never a CPU number
        found = latest_tpu_artifact(args.runs_dir or "")
        if found is not None:
            emit_stale_artifact(found[0], found[1], probe_error,
                                getattr(args, "probe_report", None))
            return 0
    size = args.size or ("tiny" if on_cpu else "8b")
    if args.slots is None:
        # int8-KV geometries halve per-slot HBM → double the slot count;
        # dense-KV geometries keep the old footprint. Mirror bench_serve's
        # dtype resolution incl. the CPU float32 override.
        dtype = args.dtype or ("int8" if size == "8b" else "bfloat16")
        if on_cpu:
            dtype = args.dtype or "float32"
        args.slots = 16 if dtype in ("int8", "int4") else 8

    if args.mode == "embed":
        rate = bench_embed(args, size, on_cpu)
        out = {
            "metric": f"embeddings/s (llama-{size}, served Embedding RPC, "
                      f"batch {args.embed_batch} x 24 tok) [BASELINE #3]",
            "value": round(rate, 2), "unit": "embeddings/s",
            "vs_baseline": None, "device": device_kind}
        if on_cpu and not args.cpu:
            out["probe_error"] = probe_error[:500]
        return emit_result(out, args)
    if args.mode == "whisper":
        rtf = bench_whisper(args, on_cpu)
        geom = "tiny-smoke, 5 s" if on_cpu else "whisper-base, 20 s"
        out = {
            "metric": f"whisper RTF ({geom} clip, served "
                      f"AudioTranscription) [BASELINE #4]",
            "value": round(rtf, 2), "unit": "audio-s/s",
            "vs_baseline": None, "device": device_kind}
        if on_cpu and not args.cpu:
            out["probe_error"] = probe_error[:500]
        return emit_result(out, args)
    if args.mode == "tp":
        # single device vs an N-wide TP mesh, SAME workload, ONE process —
        # the mesh twin of --mode paged. On CPU the mesh is virtual
        # (XLA_FLAGS host-platform devices, must be set pre-jax-init).
        n_dev = args.tensor_parallel if args.tensor_parallel > 1 else 4
        if on_cpu:
            ensure_virtual_devices(n_dev)
        import jax

        if on_cpu:
            jax.config.update("jax_platforms", "cpu")
        note("initializing device client...")
        dev = jax.devices()[0]
        device_kind = getattr(dev, "device_kind", dev.platform)
        tmp = tempfile.mkdtemp(prefix="bench-ckpt-")
        ckpt = write_synthetic_checkpoint(size, os.path.join(tmp, size))
        os.environ["LOCALAI_ALLOW_SYNTHETIC"] = "1"
        from localai_tpu.engine.loader import load_config
        from localai_tpu.models.llama import max_model_axis

        dtype_probe = args.dtype or ("int8" if size == "8b" else "bfloat16")
        if on_cpu:
            dtype_probe = args.dtype or "float32"
        cfg = load_config(ckpt, dtype=dtype_probe)
        # TP degree: explicit flag, else the widest axis every sharded dim
        # divides into (mirrors the backend's auto-TP)
        tp = args.tensor_parallel or max_model_axis(cfg, len(jax.devices()))
        if tp < 2:
            note(f"geometry shards over no more than {tp} device(s) — "
                 "tp_over_single would be vacuous")
            return 2
        single_tps, single_ttft, context, dtype, _ = bench_engine(
            args, size, on_cpu, tp=0)
        note(f"single device: {single_tps:.1f} tok/s")
        tp_tps, tp_ttft, _, _, stats = bench_engine(args, size, on_cpu, tp=tp)
        note(f"tp 1x{tp}: {tp_tps:.1f} tok/s global "
             f"({tp_tps / max(single_tps, 1e-9):.2f}x single)")
        n_params = param_count(size)
        result = {
            "metric": f"decode tok/s (llama-{size} {dtype}, tp mesh 1x{tp} "
                      f"vs single device, {args.slots} slots, ctx {context})",
            # scoreboard value = per chip, like every other row
            "value": round(tp_tps / tp, 2),
            "unit": "tok/s/chip",
            "vs_baseline": None if on_cpu else round(tp_tps / tp / 1000.0, 4),
            "tp_over_single": round(tp_tps / max(single_tps, 1e-9), 4),
            "mesh": {"data": 1, "model": tp},
            "chips": tp,
            "tok_s_global": round(tp_tps, 2),
            "tok_s_per_chip": round(tp_tps / tp, 2),
            "single_tok_s": round(single_tps, 2),
            "ttft_p50_ms": round(tp_ttft, 2),
            "single_ttft_p50_ms": round(single_ttft, 2),
            "mfu": stats.pop("mfu_cost", None),
            "device": device_kind,
            "params": n_params,
            **stats,
        }
        if on_cpu and not args.cpu:
            result["probe_error"] = probe_error[:500]
        return emit_result(result, args)
    if args.mode == "longctx":
        import jax

        if on_cpu:
            jax.config.update("jax_platforms", "cpu")
        note("initializing device client...")
        dev = jax.devices()[0]
        device_kind = getattr(dev, "device_kind", dev.platform)
        r = bench_longctx(args, size, on_cpu)
        ratio = r["long_tok_s"] / max(r["short_tok_s"], 1e-9)
        result = {
            "metric": f"longctx decode tok/s (llama-{size} {r['dtype']}, "
                      f"ctx {r['long_tokens']} {r['policy']} vs ctx 1024 "
                      f"full KV, 1 slot) [BASELINE #2f]",
            "value": round(r["long_tok_s"], 2),
            "unit": "tok/s",
            "vs_baseline": None,
            "short_tok_s": round(r["short_tok_s"], 2),
            "longctx_over_short": round(ratio, 4),
            "kv_blocks_peak": r["kv_blocks_peak"],
            "kv_evictions": r["kv_evictions"],
            "parity_exact": r["parity_exact"],
            "parity_cold_agreement": round(r["parity_cold_agreement"], 4),
            "cold_blocks": r["cold_blocks"],
            "device": device_kind,
        }
        if on_cpu and not args.cpu:
            result["probe_error"] = probe_error[:500]
        return emit_result(result, args)
    if args.mode == "ragged":
        import jax

        if on_cpu:
            jax.config.update("jax_platforms", "cpu")
        note("initializing device client...")
        dev = jax.devices()[0]
        device_kind = getattr(dev, "device_kind", dev.platform)
        (dense, ragged, equal, fused, pages, budget, context,
         dtype) = bench_ragged(args, size, on_cpu)
        toks_per_s = ragged["tok_s"]
        n_params = param_count(size)
        result = {
            "metric": f"serve tok/s (llama-{size} {dtype}, ragged "
                      f"mixed-length vs dense, {args.slots} slots, "
                      f"budget {budget} rows, ctx {context})",
            "value": round(toks_per_s, 2),
            "unit": "tok/s",
            "vs_baseline": None if on_cpu else round(toks_per_s / 1000.0, 4),
            "dense_tok_s": round(dense["tok_s"], 2),
            "equal_len_tok_s": round(equal["tok_s"], 2),
            "ragged_over_dense": round(
                toks_per_s / max(dense["tok_s"], 1e-9), 4),
            "mixed_over_equal": round(
                toks_per_s / max(equal["tok_s"], 1e-9), 4),
            "ttft_p50_ms": round(ragged["ttft_p50_ms"], 2),
            "ttft_p95_ms": round(ragged["ttft_p95_ms"], 2),
            "dense_ttft_p50_ms": round(dense["ttft_p50_ms"], 2),
            "dense_ttft_p95_ms": round(dense["ttft_p95_ms"], 2),
            "budget_utilization": ragged["budget_utilization"],
            "ragged_dispatches": int(
                ragged["metrics"].get("ragged_dispatches", 0)),
            # single-step leg dispatch stats first: when the fused leg ran,
            # its measured-window steps_per_dispatch below must win
            **dispatch_stats(ragged["metrics"]),
            # fused multi-step leg (ISSUE 16) — absent keys mean the leg
            # was disabled (--ragged-loop-steps 0/1), so benchdiff's
            # both-sides rule skips the ratio against pre-fused artifacts
            **({} if fused is None else {
                "ragged_fused_tok_s": round(fused["tok_s"], 2),
                "fused_over_ragged": round(
                    fused["tok_s"] / max(toks_per_s, 1e-9), 4),
                "fused_ttft_p50_ms": round(fused["ttft_p50_ms"], 2),
                "steps_per_dispatch": fused["steps_per_dispatch"],
                "loop_exit_reasons": fused["loop_exit_reasons"],
            }),
            "mesh": None,
            "chips": 1,
            "tok_s_global": round(toks_per_s, 2),
            "tok_s_per_chip": round(toks_per_s, 2),
            "mfu": (ragged.get("sched") or {}).get("mfu"),
            "pad_rows_frac": (ragged.get("sched") or {}).get(
                "pad_rows_frac"),
            "reason_codes": (ragged.get("sched") or {}).get(
                "reason_codes") or {},
            "rooflines": (ragged.get("sched") or {}).get("rooflines") or {},
            "device": device_kind,
            "params": n_params,
        }
        if on_cpu and not args.cpu:
            result["probe_error"] = probe_error[:500]
        return emit_result(result, args)
    if args.mode == "session":
        import jax

        if on_cpu:
            jax.config.update("jax_platforms", "cpu")
        note("initializing device client...")
        dev = jax.devices()[0]
        device_kind = getattr(dev, "device_kind", dev.platform)
        r = bench_session(args, size, on_cpu)
        result = {
            "metric": f"session turn-2 TTFT ms (llama-{size} {r['dtype']}, "
                      f"{r['session_tokens']}-token conversation, host KV "
                      f"tier {r['budget_bytes'] // (1 << 20)} MB, "
                      f"{r['kv_pages']}-block device pool)",
            "value": round(r["ttft2_host_ms"], 2),
            "unit": "ms",
            "vs_baseline": None,
            "ttft1_ms": round(r["ttft1_ms"], 2),
            "ttft2_warm_ms": round(r["ttft2_warm_ms"], 2),
            "ttft2_reprefill_ms": round(r["ttft2_reprefill_ms"], 2),
            "ttft2_restart_ms": round(r["ttft2_restart_ms"], 2),
            # lower-better gate: host-tier turn-2 TTFT over turn-1 full
            # prefill (re-admission should beat re-running the prefill)
            "turn2_over_turn1_ttft": round(
                r["ttft2_host_ms"] / max(r["ttft1_ms"], 1e-9), 4),
            # higher-better twin: re-prefill baseline over host-tier TTFT
            "readmit_speedup": round(
                r["ttft2_reprefill_ms"] / max(r["ttft2_host_ms"], 1e-9), 4),
            "restart_over_warm_ttft": round(
                r["ttft2_restart_ms"] / max(r["ttft2_warm_ms"], 1e-9), 4),
            "readmitted_blocks": r["readmitted_blocks"],
            "restart_readmitted_blocks": r["restart_readmitted_blocks"],
            # preempt/resume leg (ISSUE 19): TTFT-to-next-token resuming a
            # spill-drained generation via the survivor pool over the
            # re-prefill fallback — higher-better ratio gated in benchdiff
            # (acceptance: resume TTFT <= 0.75x re-prefill, i.e. >= 1.33)
            "ttft_resume_ms": round(r["ttft_resume_ms"], 2),
            "ttft_resume_reprefill_ms": round(
                r["ttft_resume_reprefill_ms"], 2),
            "resume_speedup": round(
                r["ttft_resume_reprefill_ms"]
                / max(r["ttft_resume_ms"], 1e-9), 4),
            "preempt_tokens": r["preempt_tokens"],
            "preempt_spilled_blocks": r["preempt_spilled_blocks"],
            "resume_readmits": r["resume_readmits"],
            "resume_reprefills": r["resume_reprefills"],
            "parity_resume": bool(r["parity_resume"]),
            "parity_resume_reprefill": bool(r["parity_resume_reprefill"]),
            "parity_host": bool(r["parity_host"]),
            "parity_restart": bool(r["parity_restart"]),
            "parity_reprefill": bool(r["parity_reprefill"]),
            "kv_host_bytes_peak": r["kv_host_bytes_peak"],
            "kv_host_budget_bytes": r["budget_bytes"],
            "budget_respected": bool(
                r["kv_host_bytes_peak"] <= r["budget_bytes"]),
            "kv_host_spills": r["kv_host_spills"],
            "kv_host_evictions": r["kv_host_evictions"],
            "device": device_kind,
        }
        if on_cpu and not args.cpu:
            result["probe_error"] = probe_error[:500]
        return emit_result(result, args)
    if args.mode == "soup":
        import jax

        if on_cpu:
            jax.config.update("jax_platforms", "cpu")
        note("initializing device client...")
        dev = jax.devices()[0]
        device_kind = getattr(dev, "device_kind", dev.platform)
        r, pages, budget, context, dtype, gamma = bench_soup(
            args, size, on_cpu)
        toks_per_s = r["tok_s"]
        result = {
            "metric": f"serve tok/s (llama-{size} {dtype}, mixed-tenant "
                      f"soup on one draft+ragged engine, {args.slots} "
                      f"slots, gamma {gamma}, budget {budget} rows, "
                      f"ctx {context})",
            "value": round(toks_per_s, 2),
            "unit": "tok/s",
            "vs_baseline": None,
            "plain_tok_s": round(r["plain_tok_s"], 2),
            "constrained_over_plain": round(
                toks_per_s / max(r["plain_tok_s"], 1e-9), 4),
            "per_tenant_paths": r["per_tenant_paths"],
            "dense_fallback_dispatches": r["dense_fallback_dispatches"],
            "dense_fallback_reasons": r.get("dense_fallback_reasons") or {},
            "compile_count_delta": r["compile_count_delta"],
            "grammar_table_states": r["grammar_table_states"],
            "draft_acceptance": r["draft_acceptance"],
            "ragged_dispatches": int(
                r["metrics"].get("ragged_dispatches", 0)),
            "mfu": (r.get("sched") or {}).get("mfu"),
            "budget_utilization": (r.get("sched") or {}).get(
                "budget_utilization"),
            "pad_rows_frac": (r.get("sched") or {}).get("pad_rows_frac"),
            "reason_codes": (r.get("sched") or {}).get("reason_codes") or {},
            "rooflines": (r.get("sched") or {}).get("rooflines") or {},
            "device": device_kind,
            **dispatch_stats(r["metrics"]),
        }
        if on_cpu and not args.cpu:
            result["probe_error"] = probe_error[:500]
        return emit_result(result, args)
    if args.mode == "paged":
        import jax

        if on_cpu:
            jax.config.update("jax_platforms", "cpu")
        note("initializing device client...")
        dev = jax.devices()[0]
        device_kind = getattr(dev, "device_kind", dev.platform)
        (dense_tps, dense_ttft, toks_per_s, ttft_ms, pages, context,
         dtype, stats) = bench_paged(args, size, on_cpu)
        n_params = param_count(size)
        result = {
            "metric": f"decode tok/s/chip (llama-{size} {dtype}, paged "
                      f"{pages} blocks vs dense, {args.slots} slots, "
                      f"ctx {context})",
            "value": round(toks_per_s, 2),
            "unit": "tok/s",
            "vs_baseline": None if on_cpu else round(toks_per_s / 1000.0, 4),
            "dense_tok_s": round(dense_tps, 2),
            "paged_over_dense": round(toks_per_s / max(dense_tps, 1e-9), 4),
            "mesh": None,
            "chips": 1,
            "tok_s_global": round(toks_per_s, 2),
            "tok_s_per_chip": round(toks_per_s, 2),
            "ttft_p50_ms": round(ttft_ms, 2),
            "dense_ttft_p50_ms": round(dense_ttft, 2),
            "mfu": stats.pop("mfu_cost", None),
            "device": device_kind,
            "params": n_params,
            **stats,
        }
        if on_cpu and not args.cpu:
            result["probe_error"] = probe_error[:500]
        return emit_result(result, args)
    if args.mode == "serve":
        # the parent process stays JAX-free: the backend subprocess owns the
        # accelerator, exactly like production serving
        toks_per_s, ttft_ms, context, dtype, stats = bench_serve(
            args, size, on_cpu)
    else:
        if on_cpu and args.tensor_parallel > 1:
            ensure_virtual_devices(args.tensor_parallel)
        import jax

        if on_cpu:
            jax.config.update("jax_platforms", "cpu")
        note("initializing device client...")
        dev = jax.devices()[0]
        device_kind = getattr(dev, "device_kind", dev.platform)
        toks_per_s, ttft_ms, context, dtype, stats = bench_engine(
            args, size, on_cpu)

    n_params = param_count(size)
    # a TP run measures GLOBAL tok/s over `chips` devices: the scoreboard
    # value and MFU normalize per chip, and the mesh shape rides the JSON so
    # a TP number can never be silently compared against a single-chip one
    chips = args.tensor_parallel if args.tensor_parallel > 1 else 1

    # BASELINE.md's north star is tok/s/chip for the flagship on a REAL chip:
    # a CPU run is a harness smoke, not a comparable number.
    paged = f", paged {args.kv_pages} blocks" if args.kv_pages else ""
    tp_tag = f", tp 1x{chips}" if chips > 1 else ""
    result = {
        "metric": f"decode tok/s/chip (llama-{size} {dtype}, {args.mode} path, "
                  f"{args.slots} slots, ctx {context}{paged}{tp_tag})",
        "value": round(toks_per_s / chips, 2),
        "unit": "tok/s",
        "vs_baseline": None if on_cpu else round(toks_per_s / chips / 1000.0,
                                                 4),
        "mesh": {"data": 1, "model": chips} if chips > 1 else None,
        "chips": chips,
        "tok_s_global": round(toks_per_s, 2),
        "tok_s_per_chip": round(toks_per_s / chips, 2),
        "ttft_p50_ms": round(ttft_ms, 2),
        "mfu": stats.pop("mfu_cost", None),
        "device": device_kind,
        "params": n_params,
        **stats,
    }
    if on_cpu and not args.cpu:
        result["probe_error"] = probe_error[:500]
    return emit_result(result, args)


if __name__ == "__main__":
    sys.exit(main())
