"""Bisect the decode step on the real chip: where do the non-floor ms go?

decode_step at B=32/ctx1024 int8 measures ~50 ms against a ~12 ms weight
stream floor (TPU_VALIDATION.md). This times each constituent in isolation
and a cumulative knockout chain:

  - full decode_step
  - layer stack with attention + cache-write knocked out (pure matmul chain)
  - layer stack with ONLY cache-write knocked out
  - cache write alone (layer-scan of quantized scatters)
  - lm_head alone, sampling alone (known), embed+rope overhead

Usage: python tools/profile_step_bisect.py [--slots 16,32] [--ctx 1024]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, n=20, warmup=3):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--slots", default="16,32")
    ap.add_argument("--ctx", type=int, default=1024)
    ap.add_argument("--size", default="8b")
    ap.add_argument("--cpu", action="store_true", help="local smoke")
    args = ap.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from bench import write_synthetic_checkpoint
    import tempfile

    os.environ["LOCALAI_ALLOW_SYNTHETIC"] = "1"
    from localai_tpu.engine.loader import load_config, load_params
    from localai_tpu.models import llama as M
    from localai_tpu.ops.rope import apply_rope, rope_table
    from localai_tpu.ops.quant import qmatmul

    tmp = tempfile.mkdtemp(prefix="bisect-")
    ckpt = write_synthetic_checkpoint(args.size, tmp)
    cfg = load_config(ckpt, dtype="int8")
    params = load_params(ckpt, cfg, dtype="int8")
    jax.block_until_ready(params)
    dev = jax.devices()[0]
    print(f"device: {getattr(dev, 'device_kind', dev.platform)}")

    T = args.ctx
    cos, sin = rope_table(cfg.rope, T)
    for B in [int(s) for s in args.slots.split(",")]:
        kc, vc = M.init_kv_cache(cfg, B, T, cache_type="int8")
        tokens = jnp.zeros((B,), jnp.int32)
        lengths = jnp.full((B,), T - 8, jnp.int32)
        active = jnp.ones((B,), bool)

        full = jax.jit(lambda p, t, l, kc, vc, a:
                       M.decode_step(p, cfg, t, l, cos, sin, kc, vc, a))
        ms_full = timeit(full, params, tokens, lengths, kc, vc, active)

        # pure matmul chain: per-layer qkv+wo+mlp, no attention / no writes
        def matmul_chain(p, t):
            x = p["embed"].astype(cfg.jdtype)[t][:, None, :]

            def layer(x, lp):
                h = M.rms_norm(x, lp["attn_norm"], cfg.rms_eps)
                q, k, v = M._qkv(h, lp, cfg)
                # stand-in for attention output with the right shape
                a = q.reshape(B, 1, -1)
                x = x + qmatmul(a, lp["wo"])
                h = M.rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
                x = x + M._mlp(h, lp, cfg)
                return x, None

            x, _ = jax.lax.scan(layer, x, p["layers"])
            x = M.rms_norm(x, p["final_norm"], cfg.rms_eps)
            return M._lm_head(x[:, 0].astype(jnp.float32), p)
        ms_mm = timeit(jax.jit(matmul_chain), params, tokens)

        # attention+rope, no cache write (reads the existing cache)
        positions = lengths[:, None]
        _, attn_decode = M._attn_impls(cfg, kv_quant=True)

        def no_write(p, t, l):
            x = p["embed"].astype(cfg.jdtype)[t][:, None, :]

            def layer(x, xs):
                lp, kcl, vcl = xs
                h = M.rms_norm(x, lp["attn_norm"], cfg.rms_eps)
                q, k, v = M._qkv(h, lp, cfg)
                q = apply_rope(q, cos, sin, positions)
                a = attn_decode(q, kcl, vcl, l + 1,
                                sliding_window=cfg.sliding_window)
                x = x + qmatmul(a.reshape(B, 1, -1), lp["wo"])
                h = M.rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
                x = x + M._mlp(h, lp, cfg)
                return x, None

            x, _ = jax.lax.scan(layer, x, (p["layers"], kc, vc))
            x = M.rms_norm(x, p["final_norm"], cfg.rms_eps)
            return M._lm_head(x[:, 0].astype(jnp.float32), p)
        ms_nw = timeit(jax.jit(no_write), params, tokens, lengths)

        # cache write alone: the layer scan of quantized scatters
        def write_only(kc, vc, t, l):
            k = jnp.ones((B, 1, cfg.num_kv_heads, cfg.head_dim), cfg.jdtype)

            def layer(c, xs):
                kcl, vcl = xs
                kcl, vcl = M._cache_write(kcl, vcl, k, k, jnp.arange(B),
                                          l[:, None])
                return c, (kcl, vcl)

            _, (kc, vc) = jax.lax.scan(layer, jnp.float32(0), (kc, vc))
            return kc, vc
        wo = jax.jit(write_only, donate_argnums=(0, 1))
        # donation: feed each call's output back as the next input — one
        # resident pair, no 20x cache allocation (a 23-pair pre-allocation
        # OOMs the 16 GB chip at the 8b geometry)
        pair = M.init_kv_cache(cfg, B, T, cache_type="int8")
        for _ in range(3):
            pair = wo(pair[0], pair[1], tokens, lengths)
        jax.block_until_ready(pair)
        t0 = time.perf_counter()
        for _ in range(20):
            pair = wo(pair[0], pair[1], tokens, lengths)
        jax.block_until_ready(pair)
        ms_w = (time.perf_counter() - t0) / 20 * 1e3
        del pair

        print(f"[B={B:3d}] full {ms_full:7.2f} | matmul-chain {ms_mm:7.2f} | "
              f"+attn(no-write) {ms_nw:7.2f} | write-only {ms_w:7.2f} | "
              f"attn-cost {ms_nw - ms_mm:6.2f} | write-cost "
              f"{ms_full - ms_nw:6.2f}")


if __name__ == "__main__":
    main()
