"""Measure the tunneled-TPU dispatch/transfer round trip — the TTFT floor.

A single-request TTFT on the idle engine is ~3 sequential device
interactions (arg upload -> admit+decode execute -> token fetch); if the
axon tunnel's RTT is hundreds of ms, TTFT is RTT-bound, not compute-bound.

Prints: trivial-op round trip, small-upload round trip, small-download
round trip, and a chained admit-shaped sequence.
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def med(xs):
    return sorted(xs)[len(xs) // 2]


def main():
    dev = jax.devices()[0]
    print(f"device: {getattr(dev, 'device_kind', dev.platform)}")

    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((8, 128), jnp.float32)
    f(x).block_until_ready()  # compile

    # full round trip: dispatch trivial op + block
    ts = []
    for _ in range(20):
        t0 = time.perf_counter()
        f(x).block_until_ready()
        ts.append((time.perf_counter() - t0) * 1e3)
    print(f"dispatch+block roundtrip: p50 {med(ts):.1f} ms "
          f"(min {min(ts):.1f}, max {max(ts):.1f})")

    # host->device upload of a small buffer (admission ids-sized)
    ids = np.zeros((8, 128), np.int32)
    ts = []
    for _ in range(20):
        t0 = time.perf_counter()
        jax.device_put(ids).block_until_ready()
        ts.append((time.perf_counter() - t0) * 1e3)
    print(f"small upload: p50 {med(ts):.1f} ms")

    # device->host download (token fetch-sized)
    y = f(x)
    ts = []
    for _ in range(20):
        t0 = time.perf_counter()
        np.asarray(y)
        ts.append((time.perf_counter() - t0) * 1e3)
    print(f"small download: p50 {med(ts):.1f} ms")

    # chained: upload -> op -> download (one admit+decode+fetch shape)
    ts = []
    for _ in range(20):
        t0 = time.perf_counter()
        np.asarray(f(jax.device_put(ids).astype(jnp.float32)))
        ts.append((time.perf_counter() - t0) * 1e3)
    print(f"upload+op+download chain: p50 {med(ts):.1f} ms")


if __name__ == "__main__":
    main()
