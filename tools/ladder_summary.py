"""Summarize bench_runs/*.json into a markdown table (TPU_VALIDATION.md
fodder once the watcher ladder completes)."""
import glob
import json
import os

ORDER = ["bench16b", "bench32d", "bench32b", "bench48d", "eng32p", "eng32d",
         "bench8k", "embed", "whisper"]


def main():
    rows = []
    for name in ORDER:
        path = os.path.join("bench_runs", name + ".json")
        if not os.path.exists(path):
            continue
        try:
            with open(path) as f:
                d = json.load(f)
        except Exception:
            continue
        rows.append((name, d))
    if not rows:
        print("no ladder results yet")
        return
    print("| stage | metric | value | unit | TTFT p50 | MFU | device |")
    print("|---|---|---|---|---|---|---|")
    for name, d in rows:
        print(f"| {name} | {d.get('metric', '?')} | {d.get('value')} | "
              f"{d.get('unit')} | {d.get('ttft_p50_ms', '—')} | "
              f"{d.get('mfu', '—')} | {d.get('device')} |")
    for extra in ("rtt.log", "attn_sweep.log", "bisect.log", "sampling.log"):
        p = os.path.join("bench_runs", extra)
        if os.path.exists(p):
            print(f"\n--- {extra} ---")
            with open(p) as f:
                for line in f.read().splitlines()[-12:]:
                    if "WARNING" not in line:
                        print(line)


if __name__ == "__main__":
    main()
