"""Slot-count sweep of decode attention on the real chip: where is the
B=16 -> B=32 cliff in ragged_decode_q8, and does the XLA path have it?

Usage: python tools/profile_attn_sweep.py
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, n=50, warmup=5):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n * 1e3  # ms


def main():
    from localai_tpu.ops.pallas import ragged_decode_q8
    from localai_tpu.ops.attention import mha_decode
    from localai_tpu.ops.kvcache import QuantKV, dequant

    dev = jax.devices()[0]
    print(f"device: {getattr(dev, 'device_kind', dev.platform)}")
    H, KVH, D, T = 32, 8, 128, 1024
    rng = np.random.default_rng(0)
    for B in (8, 16, 20, 24, 32, 48):
        q = jnp.asarray(rng.standard_normal((B, 1, H, D)), jnp.bfloat16)
        kq = jnp.asarray(rng.integers(-127, 127, (B, KVH, T, D)), jnp.int8)
        ks = jnp.asarray(rng.random((B, KVH, T // 128, 128)) * 0.01 + 0.01,
                         jnp.float32)
        vq = jnp.asarray(rng.integers(-127, 127, (B, KVH, T, D)), jnp.int8)
        vs = jnp.asarray(rng.random((B, KVH, T // 128, 128)) * 0.01 + 0.01,
                         jnp.float32)
        lengths = jnp.full((B,), T - 8, jnp.int32)

        pal = jax.jit(lambda q, kq, ks, vq, vs, l:
                      ragged_decode_q8(q, kq, ks, vq, vs, l))
        ms_pal = timeit(pal, q, kq, ks, vq, vs, lengths)

        def xla(q, kq, ks, vq, vs, l):
            kc = QuantKV(kq, ks)
            vc = QuantKV(vq, vs)
            return mha_decode(q, dequant(kc), dequant(vc), l)
        ms_xla = timeit(jax.jit(xla), q, kq, ks, vq, vs, lengths)

        kv_mb = 2 * B * KVH * T * D / 1e6
        floor = kv_mb / 1e3 / 819 * 1e3
        print(f"[B={B:3d}] pallas {ms_pal:7.3f} ms | xla {ms_xla:7.3f} ms | "
              f"kv {kv_mb:5.0f} MB floor {floor:5.3f} ms")


if __name__ == "__main__":
    main()
