"""CI telemetry smoke: serve a tiny model through the real process boundary
with tracing+profiling on, then write the merged Chrome-trace artifact.

This is the scoreboard-path exerciser the tier-1 CI job uploads: a
ModelManager-spawned gRPC backend (the same surface /v1/chat/completions
rides), a few concurrent PredictStream requests, then GetTrace → one
Chrome-trace JSON whose spans cover rpc → grpc → engine stages.

Usage: python tools/trace_smoke.py [--out trace_smoke.json]
Exit code is non-zero when the trace is missing the expected layers, so the
CI step is an assertion, not just an artifact producer.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["LOCALAI_TRACE"] = "1"
os.environ["LOCALAI_PROFILE"] = "1"
os.environ["LOCALAI_ALLOW_SYNTHETIC"] = "1"
os.environ["LOCALAI_NO_PREWARM"] = "1"
os.environ.setdefault("LOCALAI_JAX_PLATFORM", "cpu")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="trace_smoke.json")
    ap.add_argument("--requests", type=int, default=3)
    args = ap.parse_args()

    from bench import write_synthetic_checkpoint

    from localai_tpu import telemetry
    from localai_tpu.config import AppConfig, ModelConfig
    from localai_tpu.core.manager import ModelManager

    tmp = tempfile.mkdtemp(prefix="trace-smoke-")
    ckpt = write_synthetic_checkpoint("tiny", os.path.join(tmp, "tiny"))
    mcfg = ModelConfig.from_dict({
        "name": "smoke", "backend": "llm", "context_size": 128,
        "parallel": 2, "dtype": "float32", "prefill_buckets": [32],
        "parameters": {"model": ckpt},
    })
    manager = ModelManager(AppConfig(models_path=tmp, parallel_requests=2))
    handle = manager.load(mcfg)

    def one(i: int):
        token = telemetry.set_request_id(f"smoke-{i}")
        try:
            for _ in handle.client.predict_stream(
                    prompt_ids=[1, 2, 3, 4 + i], tokens=6, ignore_eos=True,
                    temperature=0.0, timeout=600.0):
                pass
        finally:
            telemetry.reset_request_id(token)

    threads = [threading.Thread(target=one, args=(i,))
               for i in range(args.requests)]
    [t.start() for t in threads]
    [t.join() for t in threads]

    payload = handle.client.trace()
    metrics = handle.client.metrics()
    manager.stop_all()

    events = list(payload.get("spans") or []) + telemetry.chrome_events()
    events.sort(key=lambda e: e.get("ts", 0))
    names = {os.getpid(): "trace-smoke", payload.get("pid", 0): "backend"}
    with open(args.out, "w") as fh:
        json.dump(telemetry.chrome_trace(events, names), fh)

    got = {e["name"] for e in events}
    rids = {e["args"].get("request_id") for e in events
            if e["name"] == "engine.request"}
    stages = (payload.get("profile") or {}).get("stages") or {}
    print(f"wrote {args.out}: {len(events)} events, layers={sorted(got)[:8]}")
    print(f"stage breakdown: " + ", ".join(
        f"{k}={v['total_ms']:.1f}ms" for k, v in stages.items()))
    want = {"engine.admit", "engine.sample", "grpc.PredictStream"}
    missing = want - got
    if missing:
        print(f"FAIL: trace missing layers {missing}", file=sys.stderr)
        return 1
    if not {f"smoke-{i}" for i in range(args.requests)} <= rids:
        print(f"FAIL: request ids did not round-trip ({rids})",
              file=sys.stderr)
        return 1
    if not stages:
        print("FAIL: no stage profile recorded", file=sys.stderr)
        return 1

    # SLO layer (ISSUE 11): the same scrape must carry the flat histogram
    # keys + headline percentiles, and GetTrace the percentile snapshot and
    # the flight-recorder rings with every smoke request's timeline
    from localai_tpu.telemetry import parse_flat, snapshot_from_hists

    if not any(k.startswith("hist_ttft__") for k in metrics):
        print("FAIL: GetMetrics carries no hist_ttft__* keys", file=sys.stderr)
        return 1
    if not metrics.get("ttft_ms_p50", 0) > 0:
        print("FAIL: no histogram-backed ttft_ms_p50", file=sys.stderr)
        return 1
    snap = snapshot_from_hists(parse_flat(metrics))
    n = (snap.get("ttft") or {}).get("count", 0)
    if n < args.requests:
        print(f"FAIL: SLO snapshot counts {n} requests, "
              f"expected >= {args.requests}", file=sys.stderr)
        return 1
    slo = payload.get("slo") or {}
    if (slo.get("e2e") or {}).get("count", 0) < args.requests:
        print(f"FAIL: GetTrace slo snapshot incomplete ({slo.keys()})",
              file=sys.stderr)
        return 1
    rec = payload.get("flightrec") or {}
    rec_ids = {r.get("request_id") for r in rec.get("requests") or []}
    want_ids = {f"smoke-{i}" for i in range(args.requests)}
    if not want_ids <= rec_ids:
        print(f"FAIL: flight recorder missing request timelines "
              f"({rec_ids})", file=sys.stderr)
        return 1
    print(f"SLO: ttft_p50={metrics['ttft_ms_p50']:.1f}ms "
          f"ttft_p95={metrics.get('ttft_ms_p95', 0):.1f}ms "
          f"flightrec={len(rec_ids)} timelines")

    # scheduler X-ray (ISSUE 13): the tick ledger must cross the scrape
    # boundary — sched_* keys in GetMetrics, the structured snapshot (with
    # the served ticks and at least one reason-code counter) in GetTrace
    if not metrics.get("sched_ticks_total", 0) > 0:
        print("FAIL: GetMetrics carries no sched_ticks_total", file=sys.stderr)
        return 1
    if not any(k.startswith("sched_reason__") for k in metrics):
        print("FAIL: GetMetrics carries no sched_reason__* keys",
              file=sys.stderr)
        return 1
    sched = payload.get("sched") or {}
    if sched.get("ticks_total", 0) <= 0 or not sched.get("reason_counters"):
        print(f"FAIL: GetTrace sched snapshot incomplete "
              f"({sorted(sched.keys())})", file=sys.stderr)
        return 1
    if not sched.get("recent_ticks"):
        print("FAIL: sched snapshot carries no tick records", file=sys.stderr)
        return 1
    print(f"sched: {sched['ticks_total']} ticks, "
          f"reasons={sched['reason_counters']}")
    print("trace smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
