"""Regenerate localai_tpu/backend/backend_pb2.py WITHOUT protoc.

grpc_tools/protoc are not in this image (the pb2 module is a checked-in
artifact), so schema changes go through this script instead: it parses the
current module's serialized FileDescriptorProto, applies the edits declared
in EDITS below, and rewrites the generated file — same builder scaffolding,
offsets recomputed by locating each descriptor's bytes in the new blob.

Run from the repo root:  python tools/regen_pb2.py

`--check` (the proto-drift CI gate) rebuilds the CANONICAL file from the
parsed descriptor and compares byte-for-byte without writing: any hand edit
to the scaffolding, the offsets, or the descriptor blob (which desyncs the
recomputed _serialized_start/_end) exits 1.
"""
from __future__ import annotations

import os
import re
import sys

from google.protobuf import descriptor_pb2

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PB2 = os.path.join(ROOT, "localai_tpu", "backend", "backend_pb2.py")

# (message, field name, field number, type) — applied only when missing
EDITS = [
    ("PredictOptions", "tools_json", 26,
     descriptor_pb2.FieldDescriptorProto.TYPE_STRING),
    # remaining request-deadline budget in ms (ISSUE 4): HTTP middleware →
    # gRPC client → engine, so an expired slot is evicted instead of decoded
    ("PredictOptions", "deadline_ms", 27,
     descriptor_pb2.FieldDescriptorProto.TYPE_INT64),
    # per-request phase timeline (ISSUE 11): JSON blob on the FINAL
    # Predict/PredictStream reply only — engine StepOutput.timings → the
    # llama.cpp-style `timings` block in the last SSE chunk
    ("Reply", "timings_json", 9,
     descriptor_pb2.FieldDescriptorProto.TYPE_STRING),
    # preemption-safe serving (ISSUE 19): a resume request carries its
    # ResumeToken here (prompt+emitted resubmit with RNG/dedup fixups)...
    ("PredictOptions", "resume_json", 28,
     descriptor_pb2.FieldDescriptorProto.TYPE_STRING),
    # ...and streamed replies carry checkpoints back: the FIRST chunk a
    # minimal {"v","prompt_ids"} (deterministic-replay fallback), the
    # terminal "preempted" chunk the full spill-drain token
    ("Reply", "resume_json", 10,
     descriptor_pb2.FieldDescriptorProto.TYPE_STRING),
]

# (method name, input message, output message, server_streaming) — added to
# the Backend service when missing (reuses existing messages: a new RPC needs
# no new types as long as its payload fits one — GetTrace ships spans JSON in
# Reply.message bytes)
SERVICE_EDITS = [
    ("GetTrace", "MetricsRequest", "Reply", False),
]


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    check = "--check" in argv
    src = open(PB2).read()
    m = re.search(rb"AddSerializedFile\(b'(.*)'\)",
                  src.encode(), re.DOTALL)
    if not m:
        print("could not find serialized descriptor", file=sys.stderr)
        return 1
    blob = m.group(1).decode("unicode_escape").encode("latin-1")
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.ParseFromString(blob)

    changed = False
    for msg_name, fname, fnum, ftype in EDITS:
        msg = next(t for t in fdp.message_type if t.name == msg_name)
        if any(f.name == fname for f in msg.field):
            continue
        f = msg.field.add()
        f.name = fname
        f.number = fnum
        f.label = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL
        f.type = ftype
        changed = True
    for svc in fdp.service:
        for mname, in_msg, out_msg, streaming in SERVICE_EDITS:
            if any(m.name == mname for m in svc.method):
                continue
            meth = svc.method.add()
            meth.name = mname
            meth.input_type = f".{fdp.package}.{in_msg}"
            meth.output_type = f".{fdp.package}.{out_msg}"
            if streaming:
                meth.server_streaming = True
            changed = True
    if not changed and not check:
        print("nothing to do")
        return 0

    blob = fdp.SerializeToString()

    def esc(b: bytes) -> str:
        out = []
        for ch in b:
            c = chr(ch)
            if c == "'":
                out.append("\\'")
            elif c == "\\":
                out.append("\\\\")
            elif 0x20 <= ch < 0x7F:
                out.append(c)
            elif c == "\n":
                out.append("\\n")
            elif c == "\t":
                out.append("\\t")
            elif c == "\r":
                out.append("\\r")
            else:
                out.append(f"\\x{ch:02x}")
        return "".join(out)

    # offsets: each descriptor's serialized bytes located in the file blob
    # (what protoc's _serialized_start/_end record)
    offsets = []

    def walk(prefix, messages):
        for t in messages:
            sub = t.SerializeToString()
            start = blob.find(sub)
            name = (prefix + "_" + t.name).upper()
            offsets.append((name, start, start + len(sub)))
            walk(prefix + "_" + t.name, t.nested_type)

    walk("", fdp.message_type)
    for s in fdp.service:
        sub = s.SerializeToString()
        start = blob.find(sub)
        offsets.append(("_" + s.name.upper(), start, start + len(sub)))

    lines = [
        "# -*- coding: utf-8 -*-",
        "# Generated by the protocol buffer compiler.  DO NOT EDIT!",
        "# source: backend.proto",
        "# (regenerated by tools/regen_pb2.py — no protoc in this image)",
        '"""Generated protocol buffer code."""',
        "from google.protobuf.internal import builder as _builder",
        "from google.protobuf import descriptor as _descriptor",
        "from google.protobuf import descriptor_pool as _descriptor_pool",
        "from google.protobuf import symbol_database as _symbol_database",
        "# @@protoc_insertion_point(imports)",
        "",
        "_sym_db = _symbol_database.Default()",
        "",
        "",
        "",
        "",
        "DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile(b'"
        + esc(blob) + "')",
        "",
        "_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())",
        "_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, 'backend_pb2',"
        " globals())",
        "if _descriptor._USE_C_DESCRIPTORS == False:",
        "",
        "  DESCRIPTOR._options = None",
        "  _PREDICTOPTIONS_LOGITBIASENTRY._options = None",
        "  _PREDICTOPTIONS_LOGITBIASENTRY._serialized_options = b'8\\001'",
        "  _METRICSRESPONSE_METRICSENTRY._options = None",
        "  _METRICSRESPONSE_METRICSENTRY._serialized_options = b'8\\001'",
        "  _MEMORYUSAGEDATA_BREAKDOWNENTRY._options = None",
        "  _MEMORYUSAGEDATA_BREAKDOWNENTRY._serialized_options = b'8\\001'",
    ]
    for name, start, end in offsets:
        if start < 0:
            continue
        lines.append(f"  {name}._serialized_start={start}")
        lines.append(f"  {name}._serialized_end={end}")
    # enums nested in messages (StatusResponse.State)
    for t in fdp.message_type:
        for e in t.enum_type:
            sub = e.SerializeToString()
            start = blob.find(sub)
            if start >= 0:
                n = f"_{t.name}_{e.name}".upper()
                lines.append(f"  {n}._serialized_start={start}")
                lines.append(f"  {n}._serialized_end={start + len(sub)}")
    lines.append("# @@protoc_insertion_point(module_scope)")
    new_src = "\n".join(lines) + "\n"
    if check:
        if new_src != src or changed:
            print("backend_pb2.py drifts from the canonical generator "
                  "output — hand-edited, or a declared EDIT is missing. "
                  "Run `python tools/regen_pb2.py` and commit the result; "
                  "never edit the generated file.", file=sys.stderr)
            return 1
        print("backend_pb2.py is canonical")
        return 0
    with open(PB2, "w") as fh:
        fh.write(new_src)
    print(f"wrote {PB2} ({len(blob)} descriptor bytes)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
