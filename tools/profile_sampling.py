"""Sampler cost breakdown on the real chip (fast path reads 1.6-1.9 ms/step
— ~8% of the decode step; the full path reads 20-74 ms and de-optimizes any
batch containing one wide-top_k request).

Times, at B=16/32 over the 128k vocab:
  - lax.top_k at width 64 / 256 / 1024 (the fast path's dominant op)
  - lax.approx_max_k at the same widths (TPU-native partial reduction)
  - full two-sort path (_filtered_sorted) for reference
  - the elementwise pipeline_logits chain alone

Usage: python tools/profile_sampling.py [--cpu]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, n=50, warmup=5):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--vocab", type=int, default=128256)
    args = ap.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from localai_tpu.ops.sampling import SamplerState, sample

    V = args.vocab
    rng = np.random.default_rng(0)
    dev = jax.devices()[0]
    print(f"device: {getattr(dev, 'device_kind', dev.platform)} vocab={V}")
    for B in (16, 32):
        logits = jnp.asarray(rng.standard_normal((B, V)), jnp.float32)
        for W in (64, 256, 1024):
            tk = jax.jit(lambda x, w=W: jax.lax.top_k(x, w))
            ms_t = timeit(tk, logits)
            ak = jax.jit(lambda x, w=W: jax.lax.approx_max_k(x, w))
            ms_a = timeit(ak, logits)
            print(f"[B={B}] W={W:5d}: lax.top_k {ms_t:7.3f} ms | "
                  f"approx_max_k {ms_a:7.3f} ms")
        st = SamplerState.init(B, V)
        fast = jax.jit(lambda lg, s: sample(lg, s, None, topk_width=64))
        ms_f = timeit(fast, logits, st)
        full = jax.jit(lambda lg, s: sample(lg, s, None))
        ms_full = timeit(full, logits, st, n=10)
        print(f"[B={B}] sample fast(64) {ms_f:7.3f} ms | full {ms_full:7.3f} ms")


if __name__ == "__main__":
    main()
