"""Whole-program lock-order analysis (the static half of localai-lockdep).

Stdlib-only AST, built on tools/lint's helpers.  Three passes:

1. **Inventory** — parse every file, collect lock objects: module-level
   locks (``_TRACER_LOCK = threading.Lock()``), attribute locks
   (``self._lock = lockdep_lock("kvhost.pool")``), dataclass-field locks,
   and per-key lock dicts (``self._model_locks[name] = ...``).  Locks
   created through ``lockdep_lock("name")`` carry their hierarchy name in
   the source; the rest resolve through ``hierarchy.STATIC_IDS``.  Also
   collect the symbol tables the call resolver needs: functions, classes,
   imports, ``self.attr`` types (from ctor assignments and annotations)
   and return-annotation types.

2. **Summaries** — per function, a memoized interprocedural effects
   summary: every lock the function (or anything it calls, transitively)
   acquires, every blocking call it can reach, and every call it could
   not resolve.  Calls resolve through direct names, imports, ``self.``
   methods, typed attributes/locals, constructors, and — when the
   receiver type is unknown — a bounded class-hierarchy fan-out over the
   in-package methods of that name (≤ MAX_CHA implementations; more, or
   none, records an ``unknown`` call instead of silently dropping it).

3. **Checks** — walk each function with a held-lock stack; every
   acquisition while holding produces an edge ``outer -> inner`` checked
   against the declared hierarchy (tools/lockdep/hierarchy.py):

   - ``lock-order``     edge violating the declared ranks (rank(outer)
                        must be strictly lower)
   - ``lock-cycle``     cycle among edges the rank check could not cover
                        (unranked locks)
   - ``lock-self``      same lock (or same per-key lock CLASS) acquired
                        while held — self-deadlock / ABBA hazard
   - ``lock-blocking``  blocking call reachable **through callees** while
                        a lock is held (depth ≥ 1 — the same-function
                        case stays lint's ``lock-across-blocking``)
   - ``unranked-lock``  a lock in localai_tpu/ the hierarchy doesn't rank
   - ``bad-pragma``     ``# lockdep: allow(...)`` naming an unknown check
   - ``stale-pragma``   a lockdep pragma that no longer suppresses
                        anything

Suppression: ``# lockdep: allow(check) — reason`` with the same
statement-aware semantics as lint pragmas (same line, any line of the
statement, or alone on the line above).
"""
from __future__ import annotations

import ast
import os

from tools.lint.astutil import dotted, last_segment, walk_skip_defs
from tools.lint.core import (
    EXCLUDED_FILES, Violation, collect_pragmas, find_root, iter_py_files,
)
from tools.lint.rules_concurrency import _LOCKLIKE, _blocking_reason

from tools.lockdep import hierarchy

CHECKS = {
    "lock-order": "acquisition order contradicts the declared hierarchy",
    "lock-cycle": "cycle in the acquired-while-held graph",
    "lock-self": "same lock (or per-key lock class) acquired while held",
    "lock-blocking": "blocking call reachable through callees under a lock",
    "unranked-lock": "lock not ranked in tools/lockdep/hierarchy.py",
    "bad-pragma": "lockdep pragma naming an unknown check",
    "stale-pragma": "lockdep pragma that suppresses nothing",
}

# unresolved method calls with these names are container/string/file plumbing
# — never lock-relevant, never blocking in-process
SAFE_METHODS = {
    "append", "appendleft", "extend", "pop", "popleft", "popitem", "get",
    "setdefault", "update", "clear", "keys", "values", "items", "add",
    "discard", "remove", "insert", "index", "count", "sort", "reverse",
    "copy", "split", "rsplit", "strip", "lstrip", "rstrip", "startswith",
    "endswith", "encode", "decode", "format", "lower", "upper", "replace",
    "lstat", "exists", "read", "write", "readline", "flush", "seek",
    "tell", "fileno", "poll", "most_common", "total", "elements",
    "as_integer_ratio", "hex", "bit_length", "item", "tolist", "tobytes",
    "astype", "reshape", "sum", "mean", "max", "min", "all", "any",
    "set", "is_set", "isoformat", "timestamp", "groups", "group", "match",
    "search", "findall", "sub", "fullmatch", "title", "capitalize",
    "zfill", "partition", "rpartition", "casefold", "difference", "union",
    "intersection", "issubset", "issuperset", "symmetric_difference",
    "getsockname", "ljust", "rjust", "center", "move_to_end", "fromkeys",
    "data_as",
}
# names in annotations that are containers/typing plumbing, not classes
_ANN_PLUMBING = {
    "list", "dict", "set", "tuple", "frozenset", "type", "str", "int",
    "float", "bool", "bytes", "bytearray", "object", "None", "Optional",
    "Union", "Any", "Iterable", "Iterator", "Sequence", "Mapping",
    "MutableMapping", "Callable", "Generator", "deque", "defaultdict",
    "OrderedDict", "Counter", "List", "Dict", "Set", "Tuple", "typing",
}
# call roots that never re-enter package code (stdlib / third-party)
IGNORED_ROOTS = {
    "os", "sys", "io", "re", "json", "math", "time", "ast", "abc",
    "logging", "collections", "itertools", "functools", "contextlib",
    "dataclasses", "threading", "queue", "socket", "subprocess", "select",
    "shlex", "shutil", "tempfile", "pathlib", "hashlib", "hmac", "base64",
    "struct", "uuid", "random", "string", "textwrap", "traceback",
    "types", "typing", "warnings", "weakref", "heapq", "bisect", "copy",
    "pickle", "signal", "inspect", "tokenize", "unicodedata", "platform",
    "np", "numpy", "jax", "jnp", "grpc", "aiohttp", "web", "asyncio",
    "pytest", "ctypes", "tomllib", "yaml", "secrets", "urllib", "http",
    "email", "errno", "gc", "glob", "gzip", "zlib", "tarfile", "zipfile",
    "enum", "operator", "array", "statistics", "difflib", "fnmatch",
}
BUILTINS = {
    "print", "len", "range", "enumerate", "zip", "map", "filter", "sorted",
    "reversed", "min", "max", "sum", "abs", "round", "int", "float", "str",
    "bytes", "bytearray", "bool", "list", "tuple", "dict", "set",
    "frozenset", "isinstance", "issubclass", "getattr", "setattr",
    "hasattr", "delattr", "repr", "hash", "id", "iter", "next", "open",
    "type", "vars", "dir", "callable", "super", "format", "ord", "chr",
    "divmod", "pow", "any", "all", "memoryview", "slice", "object",
    "Exception", "ValueError", "RuntimeError", "KeyError", "TypeError",
    "AssertionError", "StopIteration", "NotImplementedError", "OSError",
    "staticmethod", "classmethod", "property", "globals", "locals",
}
MAX_CHA = 4            # fan-out cap for untyped method calls
MAX_BLOCK_DEPTH = 8    # call-path hops shown in a lock-blocking message

_LOCK_CTORS = {"threading.Lock", "threading.RLock", "Lock", "RLock"}
_LOCKDEP_CTORS = {"lockdep_lock", "lockdep.lockdep_lock"}


class LockDef:
    """One discovered lock object (or per-key lock class)."""

    __slots__ = ("static_id", "name", "per_key", "reentrant", "path",
                 "line")

    def __init__(self, static_id, name, per_key, reentrant, path, line):
        self.static_id = static_id   # module.Class.attr / module.GLOBAL
        self.name = name             # hierarchy name ("" = unranked)
        self.per_key = per_key
        self.reentrant = reentrant
        self.path = path
        self.line = line

    @property
    def label(self) -> str:
        return self.name or self.static_id

    @property
    def rank(self):
        return hierarchy.RANKS.get(self.name) if self.name else None


def _lock_ctor_info(value: ast.AST):
    """(is_lock, hierarchy_name, per_key, reentrant) for an assignment
    RHS.  Handles threading.Lock()/RLock(), lockdep_lock("name", ...),
    field(default_factory=threading.Lock) and
    field(default_factory=lambda: lockdep_lock("name"))."""
    if not isinstance(value, ast.Call):
        return (False, "", False, False)
    fname = dotted(value.func) or ""
    if fname in _LOCK_CTORS:
        return (True, "", False, fname.endswith("RLock"))
    if fname in _LOCKDEP_CTORS:
        name = ""
        if value.args and isinstance(value.args[0], ast.Constant) \
                and isinstance(value.args[0].value, str):
            name = value.args[0].value
        per_key = any(kw.arg == "per_key"
                      and isinstance(kw.value, ast.Constant)
                      and kw.value.value for kw in value.keywords)
        return (True, name, per_key, False)
    if fname in ("field", "dataclasses.field"):
        for kw in value.keywords:
            if kw.arg != "default_factory":
                continue
            v = kw.value
            if isinstance(v, ast.Lambda):
                return _lock_ctor_info(v.body)
            vd = dotted(v) or ""
            if vd in _LOCK_CTORS:
                return (True, "", False, vd.endswith("RLock"))
    return (False, "", False, False)


def _annotation_classes(node: ast.AST) -> list[str]:
    """Bare class names mentioned in an annotation (for `x: Foo`,
    `-> list[Foo]`, `dict[str, list[Foo]]`, `"Foo"` strings)."""
    out = []
    if node is None:
        return out
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            node = ast.parse(node.value, mode="eval").body
        except SyntaxError:
            return out
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            if sub.id not in _ANN_PLUMBING:
                out.append(sub.id)
        elif isinstance(sub, ast.Attribute):
            seg = last_segment(sub)
            if seg and seg not in _ANN_PLUMBING:
                out.append(seg)
    return out


class ModuleInfo:
    def __init__(self, mod: str, path: str, tree: ast.Module):
        self.mod = mod
        self.path = path
        self.tree = tree
        self.functions: dict[str, ast.AST] = {}     # qual -> def node
        self.classes: dict[str, ast.ClassDef] = {}  # qual -> class node
        self.imports: dict[str, str] = {}           # local name -> dotted
        # (class qual, attr) -> class qual of the value
        self.attr_types: dict[tuple[str, str], str] = {}


class Analyzer:
    def __init__(self, root: str):
        self.root = root
        self.modules: dict[str, ModuleInfo] = {}
        self.files: dict[str, str] = {}             # rel path -> source
        self.locks: dict[str, LockDef] = {}         # static_id -> LockDef
        self.func_index: dict[str, tuple[ModuleInfo, ast.AST]] = {}
        self.class_index: dict[str, tuple[ModuleInfo, ast.ClassDef]] = {}
        # bare class name -> [qual] (import-free fallback + CHA)
        self.class_by_name: dict[str, list[str]] = {}
        # method name -> [(class qual, func qual)] for CHA fan-out
        self.methods_by_name: dict[str, list[tuple[str, str]]] = {}
        # func qual -> lock static_id it returns (lock getters)
        self.lock_getters: dict[str, str] = {}
        # func qual -> class qual it returns (annotation-driven)
        self.returns_class: dict[str, str] = {}
        self.summaries: dict[str, dict] = {}
        self._in_progress: set[str] = set()
        self.violations: list[Violation] = []
        # (outer label, inner label) -> [(path, line, via)]
        self.edges: dict[tuple[str, str], list[tuple[str, int, str]]] = {}
        self.unknown_calls: dict[str, int] = {}
        # labels of held locks at unresolved-call sites
        self.unknown_edges: dict[tuple[str, str], int] = {}

    # ---------------------------------------------------------- pass 1

    def load(self, targets: list[str]) -> None:
        for target in targets:
            for fp in iter_py_files(target):
                rel = os.path.relpath(os.path.abspath(fp),
                                      self.root).replace(os.sep, "/")
                if rel in EXCLUDED_FILES or rel in self.files:
                    continue
                try:
                    with open(fp, encoding="utf-8") as f:
                        src = f.read()
                except (OSError, UnicodeDecodeError) as e:
                    self.violations.append(Violation(rel, 1, "unreadable",
                                                     str(e)))
                    continue
                self.files[rel] = src
                try:
                    tree = ast.parse(src)
                except SyntaxError as e:
                    self.violations.append(Violation(
                        rel, e.lineno or 1, "syntax-error", str(e.msg)))
                    continue
                self._index_module(rel, src, tree)

    @staticmethod
    def _module_name(rel: str) -> str:
        mod = rel[:-3] if rel.endswith(".py") else rel
        mod = mod.replace("/", ".")
        if mod.endswith(".__init__"):
            mod = mod[: -len(".__init__")]
        return mod

    def _index_module(self, rel: str, src: str, tree: ast.Module) -> None:
        mod = self._module_name(rel)
        mi = ModuleInfo(mod, rel, tree)
        self.modules[mod] = mi

        for node in tree.body:
            self._index_stmt(mi, node, scope=mod, cls=None)
        # imports anywhere (function-local imports matter: http.py pulls
        # sessions_from_config inside the method that uses it)
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mi.imports[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    if a.name == "*":
                        continue
                    mi.imports[a.asname or a.name] = \
                        f"{node.module}.{a.name}"

    def _index_stmt(self, mi, node, scope, cls) -> None:
        if isinstance(node, ast.ClassDef):
            qual = f"{scope}.{node.name}"
            mi.classes[qual] = node
            self.class_index[qual] = (mi, node)
            self.class_by_name.setdefault(node.name, []).append(qual)
            for sub in node.body:
                self._index_stmt(mi, sub, scope=qual, cls=qual)
            # dataclass-field locks declared in the class body
            for sub in node.body:
                if isinstance(sub, ast.AnnAssign) and sub.value is not None \
                        and isinstance(sub.target, ast.Name):
                    self._maybe_lock(mi, f"{qual}.{sub.target.id}",
                                     sub.value, sub.lineno)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qual = f"{scope}.{node.name}"
            mi.functions[qual] = node
            self.func_index[qual] = (mi, node)
            if cls is not None:
                self.methods_by_name.setdefault(node.name, []).append(
                    (cls, qual))
                self._scan_method(mi, cls, qual, node)
            rets = _annotation_classes(node.returns)
            if len(rets) == 1:
                self.returns_class[qual] = rets[0]   # resolved lazily
        elif isinstance(node, ast.Assign) and cls is None:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self._maybe_lock(mi, f"{scope}.{t.id}", node.value,
                                     node.lineno)
        elif isinstance(node, ast.AnnAssign) and cls is None \
                and node.value is not None and isinstance(node.target,
                                                          ast.Name):
            self._maybe_lock(mi, f"{scope}.{node.target.id}", node.value,
                             node.lineno)

    def _maybe_lock(self, mi, static_id, value, lineno,
                    per_key_override=False) -> None:
        is_lock, name, per_key, reentrant = _lock_ctor_info(value)
        if not is_lock:
            return
        if not name:
            name = hierarchy.STATIC_IDS.get(static_id, "")
        self.locks[static_id] = LockDef(
            static_id, name, per_key or per_key_override
            or name in hierarchy.PER_KEY, reentrant, mi.path, lineno)

    def _scan_method(self, mi, cls, qual, fn) -> None:
        """Attribute locks, per-key lock dicts, attr types and lock
        getters declared inside a method body."""
        for node in walk_skip_defs(fn):
            targets = []
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if value is None:
                continue
            for t in targets:
                # self.X = <lock ctor> / self.X: T = ...
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    self._maybe_lock(mi, f"{cls}.{t.attr}", value,
                                     node.lineno)
                    # self.X = ClassName(...): attribute type
                    if isinstance(value, ast.Call):
                        cname = dotted(value.func)
                        if cname and cname[0].isupper() or (
                                cname and "." in cname
                                and cname.rsplit(".", 1)[1][:1].isupper()):
                            mi.attr_types[(cls, t.attr)] = cname
                    if isinstance(node, ast.AnnAssign):
                        anns = _annotation_classes(node.annotation)
                        if len(anns) == 1:
                            mi.attr_types.setdefault((cls, t.attr),
                                                     anns[0])
                # self.D[k] = <lock ctor>: per-key lock dict
                elif isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Attribute) and \
                        isinstance(t.value.value, ast.Name) and \
                        t.value.value.id == "self":
                    self._maybe_lock(mi, f"{cls}.{t.value.attr}", value,
                                     node.lineno, per_key_override=True)
        # lock getter: every return resolves to one discovered lock
        ret_ids = set()
        plain_return = False
        for node in walk_skip_defs(fn):
            if not isinstance(node, ast.Return):
                continue
            rid = self._lock_id_of_expr(mi, cls, fn, node.value)
            if rid is not None:
                ret_ids.add(rid)
            else:
                plain_return = True
        if len(ret_ids) == 1 and not plain_return:
            self.lock_getters[qual] = next(iter(ret_ids))

    def _lock_id_of_expr(self, mi, cls, fn, expr):
        """static_id if `expr` denotes a discovered lock (self.X,
        MODULE_LOCK, self.D[...], self.D.get(...), or a local assigned
        from one of those)."""
        if expr is None:
            return None
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                and cls is not None:
            sid = f"{cls}.{expr.attr}"
            return sid if sid in self.locks else None
        if isinstance(expr, ast.Name):
            sid = f"{mi.mod}.{expr.id}"
            if sid in self.locks:
                return sid
            # local variable assigned from a lock expression
            for node in walk_skip_defs(fn):
                val = None
                if isinstance(node, ast.Assign):
                    tgt_names = []
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            tgt_names.append(t.id)
                        elif isinstance(t, ast.Subscript):
                            # chained `lk = self.D[k] = ctor`
                            continue
                    if expr.id in tgt_names:
                        val = node.value
                if val is not None and not isinstance(val, ast.Name):
                    rid = self._lock_id_of_expr(mi, cls, fn, val)
                    if rid is not None:
                        return rid
                    is_lock, name, per_key, reent = _lock_ctor_info(val)
                    if is_lock and cls is not None and \
                            isinstance(node, ast.Assign):
                        # chained per-key insert: lk = self.D[k] = ctor
                        for t in node.targets:
                            if isinstance(t, ast.Subscript) and \
                                    isinstance(t.value, ast.Attribute):
                                sid = f"{cls}.{t.value.attr}"
                                if sid in self.locks:
                                    return sid
            return None
        if isinstance(expr, ast.Subscript):
            return self._dict_lock(mi, cls, expr.value)
        if isinstance(expr, ast.Call):
            # self.D.get(k) on a per-key dict, or a lock-getter call
            f = expr.func
            if isinstance(f, ast.Attribute) and f.attr == "get":
                return self._dict_lock(mi, cls, f.value)
            qual = self._resolve_call(mi, cls, fn, expr)
            if isinstance(qual, str) and qual in self.lock_getters:
                return self.lock_getters[qual]
        return None

    def _dict_lock(self, mi, cls, expr):
        """static_id when `expr` is a per-key lock dict (self.D)."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and cls is not None:
            sid = f"{cls}.{expr.attr}"
            ld = self.locks.get(sid)
            if ld is not None and ld.per_key:
                return sid
        return None

    # ------------------------------------------------------ resolution

    def _resolve_class_name(self, mi, cname):
        """Class qual for a (possibly dotted) name used in module mi."""
        if cname is None:
            return None
        parts = cname.split(".")
        head = parts[0]
        target = mi.imports.get(head)
        if target is not None:
            cand = ".".join([target] + parts[1:])
            if cand in self.class_index:
                return cand
            # `from x import mod` then mod.Class
        cand = f"{mi.mod}.{cname}"
        if cand in self.class_index:
            return cand
        if cname in self.class_index:
            return cname
        quals = self.class_by_name.get(parts[-1])
        if quals and len(quals) == 1:
            return quals[0]
        return None

    def _local_types(self, mi, cls, fn):
        """name -> class qual for locals with inferable types (memoized
        per function on the node)."""
        cached = getattr(fn, "_lockdep_local_types", None)
        if cached is not None:
            return cached
        types: dict[str, str] = {}
        # parameter annotations
        args = list(fn.args.posonlyargs) + list(fn.args.args) + \
            list(fn.args.kwonlyargs)
        for a in args:
            anns = _annotation_classes(a.annotation)
            if len(anns) == 1:
                cq = self._resolve_class_name(mi, anns[0])
                if cq:
                    types[a.arg] = cq
        for node in walk_skip_defs(fn):
            tgt = None
            value = None
            ann = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tgt, value = node.targets[0].id, node.value
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name):
                tgt, value, ann = node.target.id, node.value, \
                    node.annotation
            elif isinstance(node, (ast.For, ast.AsyncFor)) and \
                    isinstance(node.target, ast.Name):
                # `for x in <expr typed list[T]>` — element type
                cq = self._element_type(mi, cls, fn, node.iter, types)
                if cq:
                    types[node.target.id] = cq
                continue
            if tgt is None:
                continue
            if ann is not None:
                anns = _annotation_classes(ann)
                if len(anns) == 1:
                    cq = self._resolve_class_name(mi, anns[0])
                    if cq:
                        types[tgt] = cq
                        continue
            if isinstance(value, ast.Call):
                cq = self._call_result_class(mi, cls, fn, value, types)
                if cq:
                    types[tgt] = cq
        fn._lockdep_local_types = types
        return types

    def _call_result_class(self, mi, cls, fn, call, types):
        """Class qual a call returns: a constructor, or a function with a
        single-class return annotation."""
        fname = dotted(call.func)
        cq = self._resolve_class_name(mi, fname) if fname else None
        if cq:
            return cq
        qual = self._resolve_call(mi, cls, fn, call, types)
        if isinstance(qual, str):
            ret = self.returns_class.get(qual)
            if ret:
                owner_mi = self.func_index[qual][0]
                return self._resolve_class_name(owner_mi, ret)
        return None

    def _element_type(self, mi, cls, fn, expr, types):
        """Element class of an iterated expression, from return/attr
        annotations like `-> list[MCPSession]`."""
        if isinstance(expr, ast.Call):
            return self._call_result_class(mi, cls, fn, expr, types)
        if isinstance(expr, ast.Name):
            return types.get(expr.id)
        return None

    def _resolve_call(self, mi, cls, fn, call, types=None):
        """Resolve a call to a function qual, a list of quals (CHA
        fan-out), or None (not package code).  Returns "?" for calls
        that SHOULD be package code but could not be resolved."""
        f = call.func
        if isinstance(f, ast.Name):
            name = f.id
            if name in BUILTINS:
                return None
            target = mi.imports.get(name)
            if target is not None:
                if target in self.func_index:
                    return target
                root = target.split(".")[0]
                if root in IGNORED_ROOTS:
                    return None
                cq = self._resolve_class_name(mi, name)
                if cq:
                    return self._ctor_of(cq)
                return "?" if target.startswith(self._pkg_roots()) else None
            qual = f"{mi.mod}.{name}"
            if qual in self.func_index:
                return qual
            cq = self._resolve_class_name(mi, name)
            if cq:
                return self._ctor_of(cq)
            return None
        if not isinstance(f, ast.Attribute):
            return "?"
        attr = f.attr
        recv = f.value
        if isinstance(recv, (ast.Constant, ast.JoinedStr)):
            return None    # "...".join(...) and friends
        # self.method()
        if isinstance(recv, ast.Name) and recv.id == "self" and cls:
            qual = f"{cls}.{attr}"
            if qual in self.func_index:
                return qual
            # inherited methods: single in-package definition of the name
            return self._cha(attr, allow_single=True)
        # module.func() through imports
        chain = dotted(f)
        if chain:
            head = chain.split(".")[0]
            if head in IGNORED_ROOTS:
                return None
            target = mi.imports.get(head)
            if target is not None:
                cand = target + chain[len(head):]
                if cand in self.func_index:
                    return cand
                root = target.split(".")[0]
                if root in IGNORED_ROOTS:
                    return None
            cand = f"{mi.mod}.{chain}"
            if cand in self.func_index:
                return cand
        # typed receiver: self.attr.m(), local.m(), ClassName.m()
        recv_cls = None
        if isinstance(recv, ast.Attribute) and \
                isinstance(recv.value, ast.Name) and \
                recv.value.id == "self" and cls:
            owner = self.class_index[cls][0] if cls in self.class_index \
                else mi
            cname = owner.attr_types.get((cls, recv.attr))
            if cname:
                recv_cls = self._resolve_class_name(owner, cname)
        elif isinstance(recv, ast.Name):
            if types is None:
                types = self._local_types(mi, cls, fn)
            recv_cls = types.get(recv.id)
            if recv_cls is None:
                recv_cls = self._resolve_class_name(mi, recv.id) \
                    if recv.id[:1].isupper() else None
        elif isinstance(recv, ast.Call):
            recv_cls = self._call_result_class(mi, cls, fn, recv,
                                               types or {})
        if recv_cls:
            qual = f"{recv_cls}.{attr}"
            if qual in self.func_index:
                return qual
            return None    # known type, unknown method (dataclass field..)
        if attr in SAFE_METHODS:
            return None
        return self._cha(attr)

    def _cha(self, attr, allow_single=False):
        """Bounded class-hierarchy fan-out: all in-package methods named
        `attr` (≤ MAX_CHA, else unresolved)."""
        impls = self.methods_by_name.get(attr, [])
        if not impls:
            return "?"
        if allow_single and len(impls) == 1:
            return impls[0][1]
        if len(impls) <= MAX_CHA:
            return [q for _c, q in impls]
        return "?"

    def _ctor_of(self, cq):
        qual = f"{cq}.__init__"
        return qual if qual in self.func_index else None

    _pkg_cache = None

    def _pkg_roots(self):
        if self._pkg_cache is None:
            self._pkg_cache = tuple({m.split(".")[0]
                                     for m in self.modules}) or ("",)
        return self._pkg_cache

    # ------------------------------------------------------- summaries

    def summary(self, qual: str) -> dict:
        """{acquires: {static_id: via}, blocking: {(reason, via)},
        unknown: set} — transitive effects of calling `qual`."""
        memo = self.summaries.get(qual)
        if memo is not None:
            return memo
        if qual in self._in_progress:    # recursion: fixpoint at empty
            return {"acquires": {}, "blocking": set(), "unknown": set()}
        self._in_progress.add(qual)
        mi, fn = self.func_index[qual]
        cls = qual.rsplit(".", 1)[0]
        cls = cls if cls in self.class_index else None
        eff = {"acquires": {}, "blocking": set(), "unknown": set()}
        short = qual.rsplit(".", 2)
        short = ".".join(short[-2:]) if len(short) >= 2 else qual

        def add_call_effects(call, lineno):
            resolved = self._resolve_call(mi, cls, fn, call)
            quals = resolved if isinstance(resolved, list) else \
                ([resolved] if isinstance(resolved, str)
                 and resolved != "?" else [])
            if resolved == "?":
                nm = dotted(call.func) or getattr(call.func, "attr", "?")
                eff["unknown"].add(nm)
            tag = "?" if isinstance(resolved, list) else ""
            for q in quals:
                if q is None:
                    continue
                sub = self.summary(q)
                for sid, via in sub["acquires"].items():
                    eff["acquires"].setdefault(
                        sid, f"{short}:{lineno} -> {via}")
                for reason, via in sub["blocking"]:
                    hop = f"{short}:{lineno} ->{tag} {via}"
                    if hop.count("->") <= MAX_BLOCK_DEPTH:
                        eff["blocking"].add((reason, hop))
                eff["unknown"] |= sub["unknown"]

        for node in walk_skip_defs(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    sid = self._lock_id_of_expr(mi, cls, fn,
                                                item.context_expr)
                    if sid is not None:
                        eff["acquires"].setdefault(
                            sid, f"{short}:{node.lineno}")
            elif isinstance(node, ast.Call):
                # lk.acquire() on a discovered lock
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "acquire":
                    sid = self._lock_id_of_expr(mi, cls, fn,
                                                node.func.value)
                    if sid is not None:
                        eff["acquires"].setdefault(
                            sid, f"{short}:{node.lineno}")
                        continue
                reason = _blocking_reason(node)
                if reason:
                    eff["blocking"].add(
                        (reason, f"{short}:{node.lineno}"))
                    continue
                add_call_effects(node, node.lineno)
        self._in_progress.discard(qual)
        self.summaries[qual] = eff
        return eff

    # ---------------------------------------------------------- checks

    def check(self) -> None:
        for sid, ld in sorted(self.locks.items()):
            if not ld.name and ld.path.startswith("localai_tpu/"):
                self.violations.append(Violation(
                    ld.path, ld.line, "unranked-lock",
                    f"lock {sid} has no hierarchy name — create it via "
                    f"lockdep_lock(\"<name>\") and rank the name in "
                    f"tools/lockdep/hierarchy.py (see the README "
                    f"'adding a new lock' checklist)"))
            elif ld.name and ld.rank is None \
                    and ld.path.startswith("localai_tpu/"):
                self.violations.append(Violation(
                    ld.path, ld.line, "unranked-lock",
                    f"lock name {ld.name!r} is not ranked in "
                    f"tools/lockdep/hierarchy.py"))
        for qual in sorted(self.func_index):
            self._check_function(qual)
        self._check_cycles()

    def _check_function(self, qual: str) -> None:
        mi, fn = self.func_index[qual]
        cls = qual.rsplit(".", 1)[0]
        cls = cls if cls in self.class_index else None

        def visit(node, held):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new_locks = []
                for item in node.items:
                    visit(item.context_expr, held)   # pre-acquire effects
                    sid = self._lock_id_of_expr(mi, cls, fn,
                                                item.context_expr)
                    if sid is not None:
                        self._on_acquire(mi, qual, sid, node.lineno,
                                         held, via="")
                        new_locks.append(sid)
                for stmt in node.body:
                    visit(stmt, held + new_locks)
                return
            if isinstance(node, ast.Call):
                self._on_call(mi, cls, fn, qual, node, held)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fn.body:
            visit(stmt, [])

    def _on_call(self, mi, cls, fn, qual, call, held) -> None:
        # bare lk.acquire() counts as an acquisition event
        if isinstance(call.func, ast.Attribute) and \
                call.func.attr == "acquire":
            sid = self._lock_id_of_expr(mi, cls, fn, call.func.value)
            if sid is not None:
                self._on_acquire(mi, qual, sid, call.lineno, held, via="")
                return
        if _blocking_reason(call):
            return          # direct blocking-under-lock is lint's rule
        resolved = self._resolve_call(mi, cls, fn, call)
        if resolved == "?":
            nm = dotted(call.func) or getattr(call.func, "attr", "?")
            self.unknown_calls[nm] = self.unknown_calls.get(nm, 0) + 1
            for h in held:
                self.unknown_edges[(self._label(h), f"?{nm}()")] = \
                    self.unknown_edges.get(
                        (self._label(h), f"?{nm}()"), 0) + 1
            return
        quals = resolved if isinstance(resolved, list) else \
            ([resolved] if isinstance(resolved, str) else [])
        maybe = " (possible receiver)" if isinstance(resolved, list) \
            else ""
        for q in quals:
            sub = self.summary(q)
            for sid, via in sub["acquires"].items():
                self._on_acquire(mi, qual, sid, call.lineno, held,
                                 via=f" via {via}{maybe}")
            if held:
                for reason, via in sub["blocking"]:
                    self.violations.append(Violation(
                        mi.path, call.lineno, "lock-blocking",
                        f"{reason} reachable while holding "
                        f"{self._label(held[-1])!r}: {via}{maybe} — "
                        f"snapshot under the lock, block outside it"))

    def _label(self, sid: str) -> str:
        ld = self.locks.get(sid)
        return ld.label if ld else sid

    def _on_acquire(self, mi, qual, sid, lineno, held, via) -> None:
        ld = self.locks.get(sid)
        if ld is None:
            return
        for h in held:
            hd = self.locks.get(h)
            if hd is None:
                continue
            if h == sid or (hd.name and hd.name == ld.name):
                if ld.reentrant and h == sid:
                    continue
                kind = ("per-key class" if ld.per_key else "lock")
                self.violations.append(Violation(
                    mi.path, lineno, "lock-self",
                    f"{ld.label!r} acquired while the same {kind} is "
                    f"already held{via} — "
                    + ("two keys of one per-key class nest: ABBA "
                       "deadlock between threads"
                       if ld.per_key else "self-deadlock")))
                continue
            self.edges.setdefault((hd.label, ld.label), []).append(
                (mi.path, lineno, via))
            if hd.rank is not None and ld.rank is not None \
                    and hd.rank >= ld.rank:
                self.violations.append(Violation(
                    mi.path, lineno, "lock-order",
                    f"{ld.label!r} (rank {ld.rank}) acquired while "
                    f"holding {hd.label!r} (rank {hd.rank}){via} — "
                    f"the hierarchy requires {ld.label!r} outside "
                    f"{hd.label!r}; see tools/lockdep/hierarchy.py"))

    def _check_cycles(self) -> None:
        """Cycles among edges the rank check could not adjudicate (at
        least one unranked endpoint)."""
        ranked = hierarchy.RANKS
        adj: dict[str, set[str]] = {}
        for (a, b) in self.edges:
            if a in ranked and b in ranked:
                continue   # rank check owns fully-ranked edges
            adj.setdefault(a, set()).add(b)
        state: dict[str, int] = {}
        stack: list[str] = []

        def dfs(n):
            state[n] = 1
            stack.append(n)
            for m in sorted(adj.get(n, ())):
                if state.get(m, 0) == 1:
                    cyc = stack[stack.index(m):] + [m]
                    path, line, _via = self.edges[(n, m)][0]
                    self.violations.append(Violation(
                        path, line, "lock-cycle",
                        "acquired-while-held cycle: "
                        + " -> ".join(cyc)))
                elif state.get(m, 0) == 0:
                    dfs(m)
            stack.pop()
            state[n] = 2

        for n in sorted(adj):
            if state.get(n, 0) == 0:
                dfs(n)

    # ----------------------------------------------------- suppression

    def filtered(self) -> list[Violation]:
        """Apply `# lockdep: allow(...)` pragmas; emit bad-pragma and
        stale-pragma for the pragma hygiene itself."""
        out: list[Violation] = []
        by_path: dict[str, list[Violation]] = {}
        for v in self.violations:
            by_path.setdefault(v.path, []).append(v)
        for path, src in self.files.items():
            allowed, raw = collect_pragmas(src, tag="lockdep")
            vs = by_path.pop(path, [])
            # contributors[line][name] = pragma lines granting `name` there
            contributors: dict[int, dict[str, set[int]]] = {}
            src_lines = src.splitlines()
            for pln, names_raw in raw:
                names = {n.strip() for n in names_raw.split(",")
                         if n.strip()}
                covers = {pln}
                text = src_lines[pln - 1] if pln <= len(src_lines) else ""
                if text.lstrip().startswith("#"):   # standalone pragma
                    nxt = pln
                    while nxt < len(src_lines):
                        stripped = src_lines[nxt].strip()
                        if stripped and not stripped.startswith("#"):
                            covers.add(nxt + 1)
                            break
                        nxt += 1
                for ln in covers:
                    for name in names:
                        contributors.setdefault(ln, {}).setdefault(
                            name, set()).add(pln)
            spans: list[tuple[int, int]] = []
            try:
                tree = ast.parse(src)
            except SyntaxError:
                tree = None
            if tree is not None:
                for node in ast.walk(tree):
                    if isinstance(node, ast.stmt) and \
                            getattr(node, "end_lineno", None):
                        spans.append((node.lineno, node.end_lineno))

            def pragma_lines(line):
                """Lines whose pragmas cover `line` (own line + the
                enclosing innermost statement's lines)."""
                cover = {line}
                best = None
                for s, e in spans:
                    if s <= line <= e and (best is None or
                                           (e - s) < (best[1] - best[0])):
                        best = (s, e)
                if best:
                    cover.update(range(best[0], best[1] + 1))
                return cover

            used: set[tuple[int, str]] = set()
            for v in vs:
                sup = False
                for ln in pragma_lines(v.line):
                    plns = contributors.get(ln, {}).get(v.rule)
                    if plns:
                        used.update((p, v.rule) for p in plns)
                        sup = True
                if not sup:
                    out.append(v)
            for pln, names_raw in raw:
                for name in (n.strip() for n in names_raw.split(",")):
                    if not name:
                        continue
                    if name not in CHECKS:
                        out.append(Violation(
                            path, pln, "bad-pragma",
                            f"lockdep pragma allows unknown check "
                            f"{name!r}; known: "
                            f"{', '.join(sorted(CHECKS))}"))
                    elif (pln, name) not in used:
                        out.append(Violation(
                            path, pln, "stale-pragma",
                            f"lockdep pragma allow({name}) suppresses "
                            f"nothing — remove it (stale allowlists rot)"))
        for vs in by_path.values():
            out.extend(vs)
        out.sort(key=lambda v: (v.path, v.line, v.rule))
        return out


def run_paths(targets: list[str], root: str | None = None):
    """Analyze every .py file under `targets`; returns (violations,
    analyzer) — violations already pragma-filtered."""
    root = os.path.abspath(root or find_root(targets[0] if targets
                                             else "."))
    an = Analyzer(root)
    an.load(targets)
    an.check()
    return an.filtered(), an
