"""CLI: `python -m tools.lockdep [paths...]` — whole-program lock-order
analysis.  Emits `file:line check message` per violation and exits nonzero
when any survive their `# lockdep: allow(...)` pragmas.  Stdlib-only: the
CI gate runs it before any dependency install."""
from __future__ import annotations

import argparse
import sys

from tools.lockdep.analysis import CHECKS, run_paths


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lockdep",
        description="localai-tpu whole-program lock-order analysis")
    ap.add_argument("paths", nargs="*",
                    default=["localai_tpu", "tools", "tests"],
                    help="files/directories to analyze (default: the tree)")
    ap.add_argument("--list-checks", action="store_true",
                    help="print the check catalog and exit")
    ap.add_argument("--graph", action="store_true",
                    help="dump the acquired-while-held edge graph "
                         "(including unresolved-call edges) and exit 0")
    ap.add_argument("--locks", action="store_true",
                    help="dump the discovered lock inventory and exit 0")
    ap.add_argument("--statistics", action="store_true",
                    help="append per-check violation counts and the "
                         "unresolved-call tally")
    args = ap.parse_args(argv)

    if args.list_checks:
        for name, desc in sorted(CHECKS.items()):
            print(f"{name:16s} {desc}")
        return 0

    violations, an = run_paths(args.paths)

    if args.locks:
        for sid, ld in sorted(an.locks.items()):
            kind = "per-key " if ld.per_key else ""
            rank = f"rank {ld.rank}" if ld.rank is not None else "UNRANKED"
            print(f"{ld.label:28s} {rank:>10s}  {kind}{sid}  "
                  f"({ld.path}:{ld.line})")
        return 0
    if args.graph:
        for (a, b), sites in sorted(an.edges.items()):
            path, line, via = sites[0]
            extra = f" (+{len(sites) - 1} more)" if len(sites) > 1 else ""
            print(f"{a} -> {b}  [{path}:{line}{via}]{extra}")
        for (a, b), n in sorted(an.unknown_edges.items()):
            print(f"{a} -> {b}  [unresolved x{n}]")
        return 0

    for v in violations:
        print(v.render())
    if args.statistics:
        counts: dict[str, int] = {}
        for v in violations:
            counts[v.rule] = counts.get(v.rule, 0) + 1
        print("--")
        for rule, n in sorted(counts.items(), key=lambda kv: -kv[1]):
            print(f"{n:5d}  {rule}")
        print(f"--  {len(an.locks)} locks, {len(an.edges)} edges, "
              f"{sum(an.unknown_calls.values())} unresolved calls "
              f"({len(an.unknown_edges)} under a lock)")
    if violations:
        print(f"-- {len(violations)} violation(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
