"""The declared lock hierarchy — the single source of truth for lock order.

Semantics: **lower rank = outer lock = acquired first.**  The analyzer
(and the runtime tripwire's observed-order graph) records an edge
``A -> B`` whenever ``B`` is acquired while ``A`` is held; the edge is
legal iff ``rank(A) < rank(B)`` strictly.  Two locks with equal rank may
never nest in either direction (equal rank means "same level, disjoint").

Lock names come from the ``lockdep_lock("name")`` registration sites in
the package; locks not (yet) created through ``lockdep_lock`` are mapped
to names here via :data:`STATIC_IDS` (keyed by the analyzer's derived
identity ``module.Class.attr`` / ``module.GLOBAL``).  A lock the analyzer
discovers that resolves to neither is an ``unranked-lock`` violation —
that is the "adding a new lock" checklist made mechanical: create it via
``lockdep_lock`` with a name, rank the name below, and the analyzer stays
green.

The rank bands (10s gaps so new locks land between existing ones):

- 0–4      backend-process load locks (outermost: a servicer load wraps
           engine construction, warmup and prewarm end to end)
- 5–9      HTTP bridge
- 10–29    manager supervision: the per-model load lock is the OUTERMOST
           long-held lock in the serving stack — load() holds it across
           the whole spawn/health/admit sequence and takes the map lock,
           handle locks and breaker inside it.  (Note the direction: the
           map lock is INNER — it guards the maps only and is never held
           across spawn/health/RPC, per the PR 4 fix.)
- 30–39    circuit breaker
- 40–49    engine bookkeeping (submit/cancel rid maps, grammar-cache init)
- 50–59    host-KV pool + prefix digest
- 60–69    grammar matcher caches, native build lock
- 70–89    peripheral singletons (stores, explorer, config loader, MCP
           transport, distributed replicator, per-backend load locks)
- 90–99    telemetry + test-harness leaves: these locks are taken deep
           inside everything else and must never acquire anything
           themselves.
"""
from __future__ import annotations

RANKS: dict[str, int] = {
    # backend-process outermost: each servicer's load lock serializes the
    # WHOLE load/warmup/prewarm sequence — it wraps engine construction,
    # grammar precompile, KV pool priming and replicator broadcast, so
    # every in-process lock nests inside it.  (Backend servicers live in
    # separate processes; their load locks never nest with each other.)
    "backend.llm.load": 0,
    "backend.image": 1,
    "backend.hfapi": 2,
    "backend.whisper": 3,
    "backend.detect": 4,

    # HTTP bridge
    "http.mcp": 5,

    # manager supervision (manager.model is per-key: one lock per model
    # name; two model locks must never nest — the analyzer and the runtime
    # tripwire both flag same-class nesting)
    "manager.model": 10,
    "manager.map": 20,
    "manager.handle": 25,

    # resilience
    "breaker": 30,

    # engine
    "engine.submit": 40,
    "engine.grammar": 45,

    # host KV hierarchy
    "kvhost.pool": 50,
    "kvhost.digest": 55,

    # grammar / native toolchain
    "matcher.cache": 60,
    "matcher.tables": 62,
    "native.build": 65,

    # peripheral singletons
    "mcp.transport": 70,
    "stores.local": 72,
    "explorer.db": 74,
    "config.loader": 76,
    "parallel.replicator": 78,

    # telemetry + harness leaves (acquire NOTHING below them)
    "telemetry.tracer_init": 90,
    "telemetry.slo_init": 91,
    "telemetry.slo": 92,
    "telemetry.flightrec_init": 93,
    "telemetry.flightrec": 94,
    "telemetry.sched": 95,
    "telemetry.profiler": 96,
    "faults.table": 98,
    "lockdep.graph": 99,
}

# locks not created through lockdep_lock(...) — mapped from the analyzer's
# derived static identity to a hierarchy name.  Migrating a lock to
# lockdep_lock removes its row here (the registration carries the name).
STATIC_IDS: dict[str, str] = {
    "localai_tpu.mcp._StdioTransport._lock": "mcp.transport",
    "localai_tpu.stores.LocalStore._lock": "stores.local",
    "localai_tpu.explorer.Database._lock": "explorer.db",
    "localai_tpu.config.model_config.ModelConfigLoader._lock": "config.loader",
    "localai_tpu.parallel.distributed.Replicator._lock": "parallel.replicator",
    "localai_tpu.backend.llm.LLMServicer._load_lock": "backend.llm.load",
    "localai_tpu.backend.image.ImageServicer._lock": "backend.image",
    "localai_tpu.backend.hfapi.HFApiServicer._lock": "backend.hfapi",
    "localai_tpu.backend.whisper.WhisperServicer._lock": "backend.whisper",
    "localai_tpu.backend.detect.DetectServicer._lock": "backend.detect",
    "localai_tpu.testing.faults._lock": "faults.table",
    "localai_tpu.testing.lockdep._graph_lock": "lockdep.graph",
}

# names marked per-key at registration (a CLASS of locks, one per dict
# key): nesting two locks of the class is an ABBA hazard even though the
# instances differ.  lockdep_lock(per_key=True) marks these dynamically;
# this set is the static mirror.
PER_KEY: frozenset[str] = frozenset({"manager.model"})


def rank_of(name: str) -> int | None:
    return RANKS.get(name)
