"""localai-lockdep: whole-program lock-order analysis.

Stdlib-only (ast + tokenize) like tools/lint — the CI gate runs before
any dependency install.  See tools/lockdep/analysis.py for the checks and
tools/lockdep/hierarchy.py for the declared lock hierarchy; the runtime
half (LOCALAI_LOCKDEP tripwire + schedule perturber) lives in
localai_tpu/testing/lockdep.py.

    python -m tools.lockdep localai_tpu tools tests
"""
from tools.lockdep.analysis import CHECKS, Analyzer, run_paths  # noqa: F401
