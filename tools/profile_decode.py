"""On-chip decode step-time breakdown (VERDICT r4 weak #2: 35ms observed vs
~17ms int8 weight-streaming floor at 8 slots — find the missing 18ms).

Times, at several slot counts, on the real chip:
  - full jitted decode_step (int8 weights, int8 KV)
  - decode minus lm_head (tied tiny head) -> lm_head share
  - ragged_decode_q8 attention alone
  - sample() fast path alone
  - qmatmul effective bandwidth over one layer's weights vs the raw int8
    stream floor (is XLA fusing the int8->bf16 convert into the dot?)

Usage: python tools/profile_decode.py [--slots 8,16,32] [--ctx 1024]
Writes nothing; prints a table to stdout.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, n=20, warmup=3):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n * 1e3  # ms


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--slots", default="8,16,32")
    ap.add_argument("--ctx", type=int, default=1024)
    ap.add_argument("--size", default="8b")
    args = ap.parse_args()

    from bench import write_synthetic_checkpoint, param_count
    import tempfile

    os.environ["LOCALAI_ALLOW_SYNTHETIC"] = "1"
    from localai_tpu.engine.loader import load_config, load_params
    from localai_tpu.models.llama import decode_step, init_kv_cache
    from localai_tpu.ops.rope import rope_table
    from localai_tpu.ops.sampling import SamplerState, sample

    tmp = tempfile.mkdtemp(prefix="prof-")
    ckpt = write_synthetic_checkpoint(args.size, tmp)
    cfg = load_config(ckpt, dtype="int8")
    params = load_params(ckpt, cfg, dtype="int8")
    jax.block_until_ready(params)
    dev = jax.devices()[0]
    print(f"device: {getattr(dev, 'device_kind', dev.platform)}")
    n_params = param_count(args.size)
    wbytes = n_params  # int8 ~ 1 byte/param
    print(f"params: {n_params/1e9:.2f}B  int8 stream: {wbytes/1e9:.2f} GB")

    # raw int8 stream floor: reduce every weight byte once
    @jax.jit
    def stream_all(ps):
        tot = jnp.float32(0)
        for leaf in jax.tree.leaves(ps):
            tot += jnp.sum(leaf.astype(jnp.float32))
        return tot

    ms = timeit(stream_all, params, n=10)
    print(f"stream-all-params (sum reduce): {ms:.1f} ms "
          f"-> {wbytes/ms/1e6:.0f} GB/s effective")

    # qmatmul vs raw: one big layer weight
    from localai_tpu.ops.quant import qmatmul
    H, I = cfg.hidden_size, cfg.intermediate_size
    w = params["layers"]["w_gate"]
    w0 = jax.tree.map(lambda x: x[0], w)  # [H, I] int8 dict
    for B in (8, 32):
        x = jnp.ones((B, H), jnp.bfloat16)
        f = jax.jit(lambda x, w: qmatmul(x, w))
        ms = timeit(f, x, w0, n=50)
        gb = H * I / 1e9
        print(f"qmatmul [B={B}] {H}x{I} int8: {ms:.3f} ms "
              f"-> {gb/ms*1e3:.0f} GB/s (floor would be ~bw)")
        # stacked over L like the scan does
        xs = jnp.ones((B, H), jnp.bfloat16)

        def scan_mm(x, w):
            def body(c, lw):
                return c + qmatmul(x, lw)[:, :H], None
            out, _ = jax.lax.scan(body, jnp.zeros((B, H), jnp.bfloat16), w)
            return out
        f2 = jax.jit(scan_mm)
        ms = timeit(f2, xs, w, n=10)
        gb = cfg.num_layers * H * I / 1e9
        print(f"scan-qmatmul [B={B}] {cfg.num_layers}x{H}x{I}: {ms:.2f} ms "
              f"-> {gb/ms*1e3:.0f} GB/s")

    T = args.ctx
    cos, sin = rope_table(cfg.rope, T)
    for B in [int(s) for s in args.slots.split(",")]:
        kc, vc = init_kv_cache(cfg, B, T, cache_type="int8")
        sampler = SamplerState.init(B, cfg.vocab_size)
        tokens = jnp.zeros((B,), jnp.int32)
        lengths = jnp.full((B,), T - 8, jnp.int32)
        active = jnp.ones((B,), bool)

        step = jax.jit(lambda p, t, l, kc, vc, a:
                       decode_step(p, cfg, t, l, cos, sin, kc, vc, a))
        ms_full = timeit(step, params, tokens, lengths, kc, vc, active, n=20)

        # attention alone
        from localai_tpu.ops.pallas import ragged_decode_q8
        q = jnp.ones((B, 1, cfg.num_heads, cfg.head_dim), jnp.bfloat16)
        attn = jax.jit(lambda q, kq, ks, vq, vs, l:
                       ragged_decode_q8(q, kq, ks, vq, vs, l))
        ms_attn_1 = timeit(attn, q, kc.q[0], kc.s[0], vc.q[0], vc.s[0],
                           lengths, n=50)

        # sampling alone (fast path width 64)
        logits = jnp.zeros((B, cfg.vocab_size), jnp.float32)
        samp = jax.jit(lambda lg, st: sample(lg, st, None, topk_width=64))
        ms_samp = timeit(samp, logits, sampler, n=50)
        # sampling full path
        samp_full = jax.jit(lambda lg, st: sample(lg, st, None))
        ms_samp_full = timeit(samp_full, logits, sampler, n=20)

        # lm_head alone
        from localai_tpu.models.llama import _lm_head
        xlast = jnp.ones((B, H), jnp.float32)
        lmh = jax.jit(lambda x, p: _lm_head(x, p))
        ms_head = timeit(lmh, xlast, params, n=50)

        print(f"[B={B:3d} ctx={T}] decode_step {ms_full:7.2f} ms "
              f"({B/ms_full*1e3:6.0f} tok/s) | attn/layer {ms_attn_1:6.3f} "
              f"(x{cfg.num_layers}={ms_attn_1*cfg.num_layers:6.2f}) | "
              f"lm_head {ms_head:6.2f} | sample(fast) {ms_samp:6.2f} "
              f"full {ms_samp_full:6.2f}")


if __name__ == "__main__":
    main()
