#!/bin/bash
# TPU tunnel watcher — round 5 perf ladder.
#
# The axon tunnel drops for hours at a time (TPU_VALIDATION.md); this loop
# probes until the chip answers, then runs the queued ladder:
#   1. real-TPU kernel/engine tests
#   2. serving bench, 16 slots (Pallas-engaged after the probe fix)
#   3. serving bench, 32 slots over a paged KV pool
#   4. decode step-time profile
# Results land in bench_runs/; the loop exits once a bench reports a
# non-cpu device, otherwise it retries every 3 min.
cd /root/repo || exit 1
mkdir -p bench_runs
log() { echo "[$(date -u +%F" "%H:%M:%S)] $*" >> bench_runs/watch.log; }

log "watcher start (pid $$)"
while true; do
  if timeout 150 python -c "import jax; d=jax.devices()[0]; assert d.platform != 'cpu', d" 2>/dev/null; then
    log "tunnel up — starting ladder"

    log "stage 1: real-TPU tests"
    LOCALAI_TPU_TESTS=1 timeout 2400 python -m pytest tests/test_tpu_real.py -q \
      > bench_runs/tpu_tests.log 2>&1
    log "stage 1 rc=$? ($(tail -1 bench_runs/tpu_tests.log))"

    log "stage 2: bench 16 slots"
    timeout 3600 python bench.py > bench_runs/bench16.json 2> bench_runs/bench16.log
    log "stage 2 rc=$? ($(cat bench_runs/bench16.json))"

    log "stage 3: bench 32 slots, paged KV (320 blocks)"
    timeout 3600 python bench.py --slots 32 --kv-pages 320 \
      > bench_runs/bench32.json 2> bench_runs/bench32.log
    log "stage 3 rc=$? ($(cat bench_runs/bench32.json))"

    if grep -q '"device": "TPU' bench_runs/bench16.json bench_runs/bench32.json; then
      log "stage 4: decode profile"
      timeout 1800 python tools/profile_decode.py > bench_runs/profile.log 2>&1
      log "stage 4 rc=$?"
      log "ladder complete"
      break
    fi
    log "benches fell back to cpu — tunnel flaked mid-ladder; retrying"
  else
    log "tunnel down; retry in 180s"
  fi
  sleep 180
done
