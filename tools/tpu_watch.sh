#!/bin/bash
# TPU tunnel watcher — round 5 perf ladder (post-change edition).
#
# The axon tunnel drops for hours at a time (TPU_VALIDATION.md); this loop
# probes until the chip answers, then runs the queued ladder:
#   0. tunnel RTT probe                      (TTFT floor measurement)
#   1. real-TPU kernel/engine tests
#   2. serve bench, 16 slots                 (post batched-admission + bf16 lm_head)
#   3. serve bench, 32 slots DENSE int8 KV   (default-config candidate)
#   -- gate: BOTH stages 2-3 must report a real TPU device, else retry --
#   3b. serve bench, 32 slots paged KV       (unique-scatter fix validation)
#   3c. serve bench, 48 slots DENSE int8 KV  (headline-config search)
#   4. engine-mode 32 paged vs dense         (serve-vs-device split)
#   5. attention slot sweep                  (dense vs paged kernel B=8..48)
#   6. long-context serve                    (ctx 8192, 3968-token prompts)
#   7. decode step bisect                    (where the non-floor ms go)
#   8. sampling profile                      (top_k vs approx_max_k)
# Results land in bench_runs/; the loop exits after a full ladder on a real
# device, otherwise it retries every 3 min.
cd /root/repo || exit 1
mkdir -p bench_runs
log() { echo "[$(date -u +%F" "%H:%M:%S)] $*" >> bench_runs/watch.log; }

log "watcher start (pid $$)"
while true; do
  if timeout 150 python -c "import jax; d=jax.devices()[0]; assert d.platform != 'cpu', d" 2>/dev/null; then
    log "tunnel up — starting ladder"

    log "stage 0: tunnel RTT probe"
    timeout 600 python tools/rtt_probe.py > bench_runs/rtt.log 2>&1
    log "stage 0 rc=$? ($(grep roundtrip bench_runs/rtt.log | head -1))"

    log "stage 1: real-TPU tests"
    LOCALAI_TPU_TESTS=1 timeout 2400 python -m pytest tests/test_tpu_real.py -q \
      > bench_runs/tpu_tests.log 2>&1
    log "stage 1 rc=$? ($(tail -1 bench_runs/tpu_tests.log))"

    log "stage 2: serve bench 16 slots (post-change)"
    timeout 3600 python bench.py > bench_runs/bench16b.json 2> bench_runs/bench16b.log
    log "stage 2 rc=$? ($(cat bench_runs/bench16b.json))"

    log "stage 3: serve bench 32 slots DENSE int8 KV (default-config candidate)"
    timeout 3600 python bench.py --slots 32 \
      > bench_runs/bench32d.json 2> bench_runs/bench32d.log
    log "stage 3 rc=$? ($(cat bench_runs/bench32d.json))"

    if grep -q '"device": "TPU' bench_runs/bench16b.json \
        && grep -q '"device": "TPU' bench_runs/bench32d.json; then
      log "stage 3b: serve bench 32 slots, paged KV (320 blocks)"
      timeout 3600 python bench.py --slots 32 --kv-pages 320 \
        > bench_runs/bench32b.json 2> bench_runs/bench32b.log
      log "stage 3b rc=$? ($(cat bench_runs/bench32b.json))"

      log "stage 3c: serve bench 48 slots DENSE int8 KV (~11.4 GB)"
      timeout 3600 python bench.py --slots 48 \
        > bench_runs/bench48d.json 2> bench_runs/bench48d.log
      log "stage 3c rc=$? ($(cat bench_runs/bench48d.json))"

      log "stage 4: engine-mode 32 paged / 32 dense"
      timeout 1800 python bench.py --mode engine --slots 32 --kv-pages 320 \
        > bench_runs/eng32p.json 2> bench_runs/eng32p.log
      log "stage 4a rc=$? ($(cat bench_runs/eng32p.json))"
      timeout 1800 python bench.py --mode engine --slots 32 \
        > bench_runs/eng32d.json 2> bench_runs/eng32d.log
      log "stage 4b rc=$? ($(cat bench_runs/eng32d.json))"

      log "stage 5: attention sweep"
      timeout 1800 python tools/profile_attn_sweep.py > bench_runs/attn_sweep.log 2>&1
      log "stage 5 rc=$?"

      log "stage 6: long-context serve (ctx 8192, 3968-token prompts, paged)"
      timeout 3600 python bench.py --slots 16 --context 8192 \
        --prompt-len 3968 --kv-pages 600 \
        > bench_runs/bench8k.json 2> bench_runs/bench8k.log
      log "stage 6 rc=$? ($(cat bench_runs/bench8k.json))"

      log "stage 7: decode step bisect"
      timeout 1800 python tools/profile_step_bisect.py > bench_runs/bisect.log 2>&1
      log "stage 7 rc=$?"

      log "stage 8: sampling profile"
      timeout 1800 python tools/profile_sampling.py > bench_runs/sampling.log 2>&1
      log "stage 8 rc=$?"

      log "stage 9: embeddings throughput (BASELINE #3)"
      timeout 1800 python bench.py --mode embed --size 1b \
        > bench_runs/embed.json 2> bench_runs/embed.log
      log "stage 9 rc=$? ($(cat bench_runs/embed.json))"

      log "stage 10: whisper RTF (BASELINE #4)"
      timeout 1800 python bench.py --mode whisper \
        > bench_runs/whisper.json 2> bench_runs/whisper.log
      log "stage 10 rc=$? ($(cat bench_runs/whisper.json))"
      log "ladder complete"
      break
    fi
    log "benches fell back to cpu — tunnel flaked mid-ladder; retrying"
  else
    log "tunnel down; retry in 180s"
  fi
  sleep 180
done
