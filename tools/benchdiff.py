"""benchdiff: noise-aware regression gate between two bench JSON artifacts.

    python -m tools.benchdiff old.json new.json
    python -m tools.benchdiff                # bench_runs/: previous vs latest
    python -m tools.benchdiff --runs-dir bench_runs --threshold 0.85

Compares two `bench.py` result lines (or archived bench_runs/ artifacts)
per (mode, metric). Absolute tok/s on shared CI boxes swings ~2x run to
run, so the gate leans on the RATIO metrics bench.py computes inside one
process against its own denominator (ragged_over_dense,
constrained_over_plain, paged_over_dense, tp_over_single, mixed_over_equal,
longctx_over_short) plus the scale-free health fields (budget utilization,
draft acceptance, MFU, pad-row fraction): those are self-relative and
stable, so a modest threshold on them is signal, not noise. Raw
throughput is reported but only FLAGGED, never gated, unless it collapses
below the --collapse floor (default 0.33x — beyond any plausible box
swing). Counter-like invariants (compile_count_delta,
dense_fallback_dispatches) regress only when they GROW.

Exit codes: 0 ok / 1 regression / 2 usage or unreadable input. The CI
step runs it advisory (continue-on-error) until the runner archives
enough artifacts to trust the floor.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

# ratio metrics: higher is better, gate at threshold * old (floored at a
# small absolute slack so a 0.01 ratio wiggle on tiny numbers can't trip)
RATIO_KEYS = (
    "ragged_over_dense", "mixed_over_equal", "constrained_over_plain",
    "paged_over_dense", "tp_over_single", "longctx_over_short",
    "fused_over_ragged",
    # --mode session (ISSUE 17): turn-2 re-prefill TTFT over host-tier
    # re-admission TTFT — the self-relative speedup the host KV tier buys;
    # a re-admission regression shrinks it
    "readmit_speedup",
    # --mode session (ISSUE 19): re-prefill TTFT over survivor-pool resume
    # TTFT after a mid-decode preempt — the speedup the spill-drain
    # checkpoint buys; a resume-path regression shrinks it
    "resume_speedup",
    "budget_utilization", "draft_acceptance", "mfu", "stage_coverage",
)
# lower is better; gate when NEW exceeds threshold-scaled OLD.
# turn2_over_turn1_ttft is the session-mode re-admission gate (ISSUE 17):
# turn-2 TTFT through the host tier over turn-1 full-prefill TTFT — it
# GROWS when re-admission regresses, so it belongs on the inverse side
# (its RATIO_KEYS twin is readmit_speedup above)
INVERSE_KEYS = ("pad_rows_frac", "host_sync_wait_ms_per_token",
                "turn2_over_turn1_ttft")
# integer invariants: any growth is a regression (new compiles mid-stream,
# new dense fallbacks) — these are exact, not noisy
GROWTH_KEYS = ("compile_count_delta",)
# informational throughput keys: flagged when they collapse, never gated
# at the ratio threshold
THROUGHPUT_KEYS = ("value", "tok_s_per_chip", "tok_s_global")


def load(path: str) -> dict:
    with open(path) as fh:
        data = json.load(fh)
    if not isinstance(data, dict):
        raise ValueError(f"{path}: not a bench result object")
    return data


def latest_two(runs_dir: str) -> tuple[str, str]:
    """(previous, latest) artifact paths by recorded_at-then-mtime order."""
    paths = []
    for fname in os.listdir(runs_dir):
        if not fname.endswith(".json"):
            continue
        p = os.path.join(runs_dir, fname)
        try:
            with open(p) as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            continue
        if not isinstance(data, dict):
            continue
        paths.append(((data.get("recorded_at") or "", os.path.getmtime(p)),
                      p))
    if len(paths) < 2:
        raise FileNotFoundError(
            f"{runs_dir}: need at least two readable artifacts, "
            f"found {len(paths)}")
    paths.sort()
    return paths[-2][1], paths[-1][1]


def mode_of(result: dict) -> str:
    """The result's bench mode, recovered from the metric line (results
    don't carry an explicit mode field; the metric string is stable)."""
    return str(result.get("metric") or "?").split("(")[0].strip()


def _num(v):
    return v if isinstance(v, (int, float)) and not isinstance(v, bool) \
        else None


def compare(old: dict, new: dict, threshold: float,
            collapse: float) -> tuple[list[str], list[str]]:
    """Returns (regressions, notes). Only keys present in BOTH results are
    compared — bench schema growth must not fail the gate."""
    regressions, notes = [], []
    mode = mode_of(new)
    if mode_of(old) != mode:
        notes.append(f"mode mismatch ({mode_of(old)!r} vs {mode!r}) — "
                     "ratio comparison only")
    for key in RATIO_KEYS:
        o, n = _num(old.get(key)), _num(new.get(key))
        if o is None or n is None or o <= 0:
            continue
        if n < o * threshold - 0.01:
            regressions.append(
                f"{mode}: {key} {o:.4f} -> {n:.4f} "
                f"({n / o:.2f}x, floor {threshold:.2f}x)")
        else:
            notes.append(f"{mode}: {key} {o:.4f} -> {n:.4f} ok")
    for key in INVERSE_KEYS:
        o, n = _num(old.get(key)), _num(new.get(key))
        if o is None or n is None:
            continue
        if n > o / max(threshold, 1e-9) + 0.01:
            regressions.append(
                f"{mode}: {key} {o:.4f} -> {n:.4f} (grew past "
                f"{1 / threshold:.2f}x)")
    for key in GROWTH_KEYS:
        o, n = _num(old.get(key)), _num(new.get(key))
        if o is None or n is None:
            continue
        if n > o:
            regressions.append(f"{mode}: {key} {o:.0f} -> {n:.0f} (grew)")
    for key in THROUGHPUT_KEYS:
        o, n = _num(old.get(key)), _num(new.get(key))
        if o is None or n is None or o <= 0:
            continue
        if n < o * collapse:
            regressions.append(
                f"{mode}: {key} collapsed {o:.2f} -> {n:.2f} "
                f"({n / o:.2f}x < {collapse:.2f}x floor)")
        elif n < o * 0.5:
            notes.append(f"{mode}: {key} {o:.2f} -> {n:.2f} "
                         f"({n / o:.2f}x — box noise or real?)")
    return regressions, notes


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="noise-aware diff of two bench.py result JSONs")
    p.add_argument("old", nargs="?", help="baseline result JSON")
    p.add_argument("new", nargs="?", help="candidate result JSON")
    p.add_argument("--runs-dir", default="bench_runs",
                   help="artifact dir when old/new not given")
    p.add_argument("--threshold", type=float, default=0.9,
                   help="ratio-metric floor: new >= threshold * old")
    p.add_argument("--collapse", type=float, default=0.33,
                   help="raw-throughput collapse floor (beyond box noise)")
    args = p.parse_args(argv)
    if bool(args.old) != bool(args.new):
        p.error("give both OLD and NEW, or neither (bench_runs mode)")
    try:
        if args.old:
            old_path, new_path = args.old, args.new
        else:
            old_path, new_path = latest_two(args.runs_dir)
        old, new = load(old_path), load(new_path)
    except (OSError, ValueError) as e:
        print(f"benchdiff: {e}", file=sys.stderr)
        return 2
    print(f"benchdiff: {old_path} -> {new_path}")
    regressions, notes = compare(old, new, args.threshold, args.collapse)
    for line in notes:
        print(f"  note: {line}")
    for line in regressions:
        print(f"  REGRESSION: {line}")
    if regressions:
        print(f"benchdiff: {len(regressions)} regression(s)")
        return 1
    print("benchdiff: no regressions")
    return 0


if __name__ == "__main__":
    sys.exit(main())
