"""localai-lint: project-native static analysis for trace hazards, host
syncs, lock discipline, and contract drift.

Run over the tree:   python -m tools.lint localai_tpu tools tests
List the rules:      python -m tools.lint --list-rules
Suppress one site:   # lint: allow(rule-name) — reason

Rule families (see README "Static analysis" for the catalog):
  trace        host syncs + recompile hazards on the serving hot paths
  concurrency  locks across blocking calls; acquire/release try/finally
  contract     sharding-spec provenance, pb2 import discipline, pytest
               marker registration

The runtime complements (what AST analysis can't see) live in
localai_tpu/testing/tripwires.py: a jax.transfer_guard around the fused
decode dispatch and a compile-count guard for decode_step.
"""
from tools.lint.core import (   # noqa: F401
    Config, Violation, get_rules, run_paths, run_source,
)
