"""CLI: `python -m tools.lint [paths...]` — emits `file:line rule message`
per violation and exits nonzero when any survive their pragmas. This is the
CI gate; it needs nothing beyond the stdlib."""
from __future__ import annotations

import argparse
import sys

from tools.lint.core import Config, get_rules, run_paths


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="localai-tpu project-native static analysis")
    ap.add_argument("paths", nargs="*",
                    default=["localai_tpu", "tools", "tests"],
                    help="files/directories to lint (default: the tree)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--select", default="",
                    help="comma-separated rule names to run (default: all)")
    ap.add_argument("--statistics", action="store_true",
                    help="append a per-rule violation count summary")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in get_rules(Config()):
            print(f"{rule.name:28s} [{rule.family}] {rule.description}")
        return 0

    select = tuple(s.strip() for s in args.select.split(",") if s.strip())
    config = Config(select=select)
    violations = run_paths(args.paths, config)
    for v in violations:
        print(v.render())
    if args.statistics and violations:
        counts: dict[str, int] = {}
        for v in violations:
            counts[v.rule] = counts.get(v.rule, 0) + 1
        print("--")
        for rule, n in sorted(counts.items(), key=lambda kv: -kv[1]):
            print(f"{n:5d}  {rule}")
    if violations:
        print(f"-- {len(violations)} violation(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
