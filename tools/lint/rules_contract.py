"""Family (c): contract drift.

`sharding-spec-source`: PR 3 added validate_specs because a hand-written
PartitionSpec that misses an axis silently replicates a TP'd weight on every
chip. The durable fix is provenance: sharding call sites must take their
specs from the audited catalog (models/llama.param_specs and friends) or
through safe_sharding — not from an inline P('model', ...) literal.

`pb2-direct-import`: backend_pb2.py is generated (tools/regen_pb2.py); code
importing it directly bypasses the sys.path shim in backend/pb.py and, worse,
normalizes hand-editing the generated file.

`pytest-marker-registered`: an unregistered marker makes `-m slow`-style
selection silently select nothing — tier-1/slow/resilience lane splitting
depends on markers meaning what pyproject.toml says they mean."""
from __future__ import annotations

import ast

from tools.lint.astutil import call_name, dotted, last_segment
from tools.lint.core import BUILTIN_MARKERS, Violation


def _spec_has_axis_names(expr: ast.AST) -> bool:
    """True when `expr` is an inline P(...)/PartitionSpec(...) literal with at
    least one string axis name. P()/P(None, ...) is explicit replication —
    harmless, allowed anywhere."""
    if not isinstance(expr, ast.Call):
        return False
    if call_name(expr) not in ("P", "PartitionSpec",
                               "jax.sharding.PartitionSpec"):
        return False
    for a in expr.args:
        for n in ast.walk(a):
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                return True
    return False


class ShardingSpecSource:
    name = "sharding-spec-source"
    family = "contract"
    description = ("sharding spec at a NamedSharding/with_sharding_constraint/"
                   "shard_map site is an inline P(...) literal, not sourced "
                   "from param_specs/safe_sharding")

    def check(self, ctx):
        cfg = ctx.config
        if ctx.path in cfg.spec_helper_files:
            return
        approved = set(cfg.spec_sources)
        # names assigned from approved source calls are fine to pass around
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            seg = last_segment(node.func)
            spec_args: list[ast.AST] = []
            site = None
            if seg == "NamedSharding" and len(node.args) >= 2:
                spec_args, site = [node.args[1]], "NamedSharding"
            elif seg == "with_sharding_constraint" and len(node.args) >= 2:
                spec_args, site = [node.args[1]], name or seg
            elif seg in ("shard_map", "_shard_map"):
                spec_args = [kw.value for kw in node.keywords
                             if kw.arg in ("in_specs", "out_specs")]
                site = "shard_map"
            if not spec_args:
                continue
            for arg in spec_args:
                for sub in ast.walk(arg):
                    if not _spec_has_axis_names(sub):
                        continue
                    # inline literal with real axis names: only allowed when
                    # it is itself wrapped by an approved source call
                    # (e.g. safe_sharding(mesh, P(...), shape))
                    if self._under_approved_call(sub, arg, ctx, approved):
                        continue
                    yield Violation(
                        ctx.path, sub.lineno, self.name,
                        f"inline PartitionSpec with axis names at a {site} "
                        f"site — source specs from "
                        f"param_specs/kv_cache_spec/paged_pool_spec or wrap "
                        f"in safe_sharding so non-dividing axes degrade "
                        f"instead of silently replicating")
                    break

    @staticmethod
    def _under_approved_call(sub, stop, ctx, approved) -> bool:
        cur = sub
        while cur is not None and cur is not stop:
            parent = ctx.parent(cur)
            if isinstance(parent, ast.Call):
                seg = last_segment(parent.func)
                if seg in approved:
                    return True
            cur = parent
        return False


class Pb2DirectImport:
    name = "pb2-direct-import"
    family = "contract"
    description = ("direct *_pb2 import outside backend/pb.py and "
                   "tools/regen_pb2.py — bypasses the generated-file "
                   "contract")

    def check(self, ctx):
        if ctx.path in ctx.config.pb2_allowed:
            return
        for node in ast.walk(ctx.tree):
            mods: list[str] = []
            if isinstance(node, ast.Import):
                mods = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.module:
                mods = [node.module]
                mods += [f"{node.module}.{a.name}" for a in node.names]
            for mod in mods:
                leaf = mod.rsplit(".", 1)[-1]
                if not leaf.endswith("_pb2") and not leaf.endswith(
                        "_pb2_grpc"):
                    continue
                if mod.startswith("google."):
                    continue   # upstream protobuf runtime modules
                yield Violation(
                    ctx.path, node.lineno, self.name,
                    f"import of {mod!r} bypasses localai_tpu.backend.pb — "
                    f"message classes come from `from localai_tpu.backend "
                    f"import pb`; regen via tools/regen_pb2.py, never "
                    f"hand-edit backend_pb2.py")
                break


class PytestMarkerRegistered:
    name = "pytest-marker-registered"
    family = "contract"
    description = ("pytest marker used under tests/ but not registered in "
                   "pyproject.toml — `-m` selection on it silently matches "
                   "nothing")

    def check(self, ctx):
        if not ctx.path.startswith("tests/"):
            return
        known = BUILTIN_MARKERS | set(ctx.config.registered_markers)
        seen: set[tuple[str, int]] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Attribute):
                continue
            chain = dotted(node)
            if not chain or not chain.startswith("pytest.mark."):
                continue
            marker = chain.split(".")[2]
            key = (marker, node.lineno)
            if marker in known or key in seen:
                continue
            seen.add(key)
            yield Violation(
                ctx.path, node.lineno, self.name,
                f"marker {marker!r} is not registered in "
                f"[tool.pytest.ini_options].markers — register it (with a "
                f"lane note) or the tier-1/slow/tp/resilience splits can't "
                f"see it")


class StalePragma:
    """Declaration only — the detection lives in core.run_source, which is
    the one place that knows whether a pragma actually suppressed anything
    this run.  The class exists so the rule is listed, selectable, and a
    known name to bad-pragma."""

    name = "stale-pragma"
    family = "contract"
    description = ("`# lint: allow(rule)` pragma that no longer suppresses "
                   "any diagnostic — a stale allowlist entry hides the day "
                   "the violation comes back")

    def check(self, ctx):
        return ()


RULES = [ShardingSpecSource(), Pb2DirectImport(), PytestMarkerRegistered(),
         StalePragma()]
