"""Family (b): concurrency discipline.

`lock-across-blocking` is the PR 4 watchdog bug class: the seed's watchdog
held the model-map lock across Popen.wait(timeout=10), freezing every
load()/get() for the duration of a reap. `acquire-release-finally` is the
mark_busy audit from the same PR turned permanent: an acquire whose release
isn't exception-protected leaks the resource on the first RpcError.

Scope contract with `tools/lockdep` (which imports `_blocking_reason` and
`_LOCKLIKE` from here): this rule owns blocking calls in the SAME function
body as the lock; the whole-program analyzer's `lock-blocking` check owns
the transitive case — blocking reached through callees — plus lock-order
inversions against the rank hierarchy. One bug class, one pragma namespace
each: direct sites carry `# lint: allow(lock-across-blocking)`, transitive
sites `# lockdep: allow(lock-blocking)`."""
from __future__ import annotations

import ast
import re

from tools.lint.astutil import call_name, dotted, last_segment, walk_skip_defs
from tools.lint.core import Violation

_LOCKLIKE = re.compile(r"lock|mutex|sem(aphore)?$|^cond(ition)?$", re.I)

# method names that block the calling thread
_BLOCKING_ATTRS = {
    "wait", "join", "communicate", "accept", "connect", "recv", "recv_into",
    "sendall", "result", "acquire",
}
# fully-dotted blocking calls
_BLOCKING_CALLS = {
    "time.sleep", "sleep", "subprocess.run", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
    "socket.create_connection", "urlopen", "urllib.request.urlopen",
}
_BLOCKING_PREFIXES = ("requests.",)
# receiver segments that mark an RPC client object (BackendClient, gRPC
# stubs/channels) — any method call on them goes over the wire
_RPC_SEGMENTS = {"client", "stub", "channel"}
_RPC_EXEMPT_METHODS = {"close", "cancel", "done", "add_done_callback"}


def _is_string_join(recv: ast.AST) -> bool:
    """`", ".join(...)` / `os.path.join(...)` / `os.sep.join(...)` are string
    and path joins, not thread joins."""
    if isinstance(recv, (ast.Constant, ast.JoinedStr)):
        return True
    chain = dotted(recv)
    if chain and any(seg in ("path", "sep", "pathsep", "linesep")
                     for seg in chain.lower().split(".")):
        return True
    return False


def _is_lock_expr(expr: ast.AST) -> bool:
    """`with self._lock:` / `with lock:` / `with self._model_lock(name):`"""
    if isinstance(expr, ast.Call):
        expr = expr.func
    seg = last_segment(expr)
    return bool(seg and _LOCKLIKE.search(seg))


def _blocking_reason(node: ast.Call) -> str | None:
    name = call_name(node)
    if name in _BLOCKING_CALLS:
        return name
    if name and any(name.startswith(p) for p in _BLOCKING_PREFIXES):
        return name
    if isinstance(node.func, ast.Attribute):
        attr = node.func.attr
        if attr in _BLOCKING_ATTRS:
            if attr == "join" and _is_string_join(node.func.value):
                return None
            return f".{attr}()"
        if attr == "get":
            recv = last_segment(node.func.value)
            if recv and "queue" in recv.lower():
                return f"{recv}.get()"
        # RPC client call: any segment of the receiver chain names a
        # client/stub/channel
        if attr not in _RPC_EXEMPT_METHODS:
            chain = dotted(node.func)
            if chain:
                segments = chain.lower().split(".")[:-1]
                if any(s in _RPC_SEGMENTS or s.endswith("client")
                       or s.endswith("stub") for s in segments):
                    return f"RPC {chain}()"
    return None


class LockAcrossBlocking:
    name = "lock-across-blocking"
    family = "concurrency"
    description = ("lock held across a blocking call (process wait, sleep, "
                   "RPC, socket) — the PR 4 watchdog bug class")

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            locks = [item.context_expr for item in node.items
                     if _is_lock_expr(item.context_expr)]
            if not locks:
                continue
            lock_desc = dotted(locks[0]) or (
                dotted(locks[0].func) if isinstance(locks[0], ast.Call)
                else "lock")
            for stmt in node.body:
                for sub in self._walk_body(stmt):
                    if not isinstance(sub, ast.Call):
                        continue
                    reason = _blocking_reason(sub)
                    if reason:
                        yield Violation(
                            ctx.path, sub.lineno, self.name,
                            f"{reason} while holding {lock_desc!r} — "
                            f"snapshot state under the lock, do the "
                            f"blocking work outside it (seed watchdog held "
                            f"the model-map lock across Popen.wait)")

    @staticmethod
    def _walk_body(stmt):
        yield stmt
        yield from walk_skip_defs(stmt)


# acquire method → (release method, release must exist in same function)
_PAIRS = {
    "mark_busy": ("mark_idle", True),
    "acquire": ("release", False),   # bare-acquire lock usage; with-stmt
                                     # preferred, release may live elsewhere
    "begin": ("finish", False),      # telemetry spans: a span finished in
                                     # the same function must do so in a
                                     # finally (engine spans legitimately
                                     # finish in _release_slot)
}


class AcquireReleaseFinally:
    name = "acquire-release-finally"
    family = "concurrency"
    description = ("resource acquire (mark_busy, span begin, lock.acquire) "
                   "whose release is not protected by try/finally")

    def check(self, ctx):
        for fn in (n for n in ast.walk(ctx.tree)
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))):
            if fn.name in _PAIRS or fn.name in {r for r, _ in
                                                _PAIRS.values()}:
                continue   # the definitions themselves
            for acq_name, (rel_name, must_exist) in _PAIRS.items():
                acquires = self._calls(fn, acq_name)
                if not acquires:
                    continue
                releases = self._calls(fn, rel_name)
                protected = [r for r in releases
                             if self._in_finally(r, fn, ctx)]
                if releases and not protected:
                    for a in acquires:
                        yield Violation(
                            ctx.path, a.lineno, self.name,
                            f"{acq_name}() paired with {rel_name}() outside "
                            f"any finally — an exception between them leaks "
                            f"the resource; use "
                            f"{acq_name}(); try: ... finally: {rel_name}()")
                elif not releases and must_exist:
                    for a in acquires:
                        yield Violation(
                            ctx.path, a.lineno, self.name,
                            f"{acq_name}() with no {rel_name}() in the same "
                            f"function — busy accounting must be released "
                            f"in a finally at the call site")

    @staticmethod
    def _calls(fn, method: str):
        out = []
        for node in walk_skip_defs(fn):
            if isinstance(node, ast.Call):
                seg = (node.func.attr if isinstance(node.func, ast.Attribute)
                       else (node.func.id if isinstance(node.func, ast.Name)
                             else None))
                if seg == method:
                    out.append(node)
        return out

    @staticmethod
    def _in_finally(node, fn, ctx) -> bool:
        cur = node
        for anc in ctx.ancestors(node):
            if isinstance(anc, ast.Try) and any(
                    cur is s or any(cur is d for d in ast.walk(s))
                    for s in anc.finalbody):
                return True
            if anc is fn:
                return False
        return False


RULES = [LockAcrossBlocking(), AcquireReleaseFinally()]
