"""localai-lint core: file walking, pragma handling, rule dispatch.

Stdlib-only by design (ast + tokenize + tomllib) — the CI lint job must run
before any dependency install, and the analyzer itself can never be the
reason a JAX upgrade breaks the tree.

Suppression pragma (same line, or alone on the line directly above):

    x = tok.item()   # lint: allow(host-sync-item) — admission is once/request

Unknown rule names inside a pragma are themselves a violation (`bad-pragma`)
so a typo can't silently disable a check forever.
"""
from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize


@dataclasses.dataclass(frozen=True)
class Violation:
    path: str          # repo-relative, posix separators
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line} {self.rule} {self.message}"


# default hot-path scope for the JAX trace/sync family: the serving engine,
# the kernels, and the model forward passes — a host sync there stalls the
# decode pipeline for every tenant. tools/, telemetry/ and tests are host
# code where a sync is the point.
HOT_DIRS = (
    "localai_tpu/engine/",
    "localai_tpu/ops/",
    "localai_tpu/models/",
)

# files the walker never lints
EXCLUDED_FILES = {
    "localai_tpu/backend/backend_pb2.py",   # generated (tools/regen_pb2.py)
}

# pytest markers that ship with pytest / plugins we use — never need
# registration in pyproject.toml
BUILTIN_MARKERS = {
    "parametrize", "skip", "skipif", "xfail", "usefixtures",
    "filterwarnings", "tryfirst", "trylast", "timeout", "asyncio", "anyio",
}


@dataclasses.dataclass
class Config:
    hot_dirs: tuple[str, ...] = HOT_DIRS
    # files allowed to touch backend_pb2 directly: the shim that puts it on
    # sys.path, and the generator that writes it
    pb2_allowed: tuple[str, ...] = ("localai_tpu/backend/pb.py",
                                    "tools/regen_pb2.py")
    # call names whose result is an approved sharding-spec source
    spec_sources: tuple[str, ...] = (
        "param_specs", "replicated_specs", "kv_cache_spec",
        "paged_pool_spec", "safe_sharding", "shard_params",
    )
    # the one module allowed to build NamedSharding from raw specs (it
    # IMPLEMENTS safe_sharding/shard_params/constrain)
    spec_helper_files: tuple[str, ...] = ("localai_tpu/parallel/mesh.py",)
    registered_markers: frozenset[str] = frozenset()
    select: tuple[str, ...] = ()     # empty = all rules

    def in_hot_path(self, path: str) -> bool:
        return any(path.startswith(d) for d in self.hot_dirs)


def load_registered_markers(root: str) -> frozenset[str]:
    """Marker names registered in <root>/pyproject.toml (empty set if the
    file or table is missing). Uses tomllib when available (3.11+) and falls
    back to extracting the quoted strings of the `markers = [...]` array —
    the lint must run on the stock CI interpreter with zero deps."""
    pp = os.path.join(root, "pyproject.toml")
    try:
        with open(pp, "rb") as f:
            blob = f.read()
    except OSError:
        return frozenset()
    markers: list[str] = []
    try:
        import tomllib

        data = tomllib.loads(blob.decode("utf-8"))
        markers = (data.get("tool", {}).get("pytest", {})
                   .get("ini_options", {}).get("markers", []))
    except ImportError:
        m = re.search(r"^markers\s*=\s*\[(.*?)\]", blob.decode("utf-8"),
                      re.S | re.M)
        if m:
            markers = re.findall(r"\"([^\"]*)\"|'([^']*)'", m.group(1))
            markers = [a or b for a, b in markers]
    except Exception:
        return frozenset()
    names = set()
    for mk in markers:
        name = str(mk).split(":", 1)[0].strip()
        # strip a call-form registration like "timeout(seconds)"
        names.add(name.split("(", 1)[0].strip())
    return frozenset(names)


class FileContext:
    """Everything a rule needs about one parsed file."""

    def __init__(self, path: str, source: str, tree: ast.AST, config: Config):
        self.path = path
        self.source = source
        self.tree = tree
        self.config = config
        self.parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    def parent(self, node: ast.AST) -> ast.AST | None:
        return self.parents.get(node)

    def ancestors(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)


_PRAGMA = re.compile(r"#\s*lint:\s*allow\(([^)]*)\)")


def collect_pragmas(source: str, tag: str = "lint") -> tuple[
        dict[int, set[str]], list[tuple[int, str]]]:
    """Map line → rule names allowed there. A pragma comment applies to its
    own line; when the comment stands alone on a line it also covers the next
    line (for statements too long to carry a trailing comment).

    `tag` is the pragma namespace — "lint" for `# lint: allow(...)`,
    "lockdep" for the lock-order analyzer's `# lockdep: allow(...)` (same
    statement-aware semantics, separate allowlists).

    Returns (allowed-by-line, [(line, raw-names)] for pragma validation)."""
    pragma_re = _PRAGMA if tag == "lint" else re.compile(
        r"#\s*" + re.escape(tag) + r":\s*allow\(([^)]*)\)")
    allowed: dict[int, set[str]] = {}
    raw: list[tuple[int, str]] = []
    try:
        toks = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return allowed, raw
    lines = source.splitlines()
    for tok in toks:
        if tok.type != tokenize.COMMENT:
            continue
        m = pragma_re.search(tok.string)
        if not m:
            continue
        names = {n.strip() for n in m.group(1).split(",") if n.strip()}
        line = tok.start[0]
        raw.append((line, m.group(1)))
        allowed.setdefault(line, set()).update(names)
        # standalone comment → suppress the next CODE line (the pragma's
        # reason may continue over following comment lines)
        logical = tok.line[: tok.start[1]].strip()
        if not logical:
            nxt = line  # 0-based index of the line after the pragma
            while nxt < len(lines):
                stripped = lines[nxt].strip()
                if stripped and not stripped.startswith("#"):
                    allowed.setdefault(nxt + 1, set()).update(names)
                    break
                nxt += 1
    return allowed, raw


def get_rules(config: Config):
    from tools.lint import rules_concurrency, rules_contract, rules_trace

    rules = (rules_trace.RULES + rules_concurrency.RULES
             + rules_contract.RULES)
    if config.select:
        rules = [r for r in rules if r.name in config.select]
    return rules


def run_source(source: str, path: str, config: Config | None = None):
    """Lint one in-memory source blob as if it lived at `path` (repo-relative
    posix). This is the API tests/test_lint.py drives with snippets."""
    config = config or Config()
    path = path.replace(os.sep, "/")
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Violation(path, e.lineno or 1, "syntax-error", str(e.msg))]
    ctx = FileContext(path, source, tree, config)
    rule_names = {r.name for r in get_rules(Config())}  # all known, unselected
    allowed, raw_pragmas = collect_pragmas(source)

    out: list[Violation] = []
    for line, names_raw in raw_pragmas:
        for name in (n.strip() for n in names_raw.split(",")):
            if name and name not in rule_names:
                out.append(Violation(
                    path, line, "bad-pragma",
                    f"pragma allows unknown rule {name!r} — a typo here "
                    f"would silently disable nothing; known rules: "
                    f"run with --list-rules"))
    # a violation anywhere in a multi-line statement is covered by a pragma
    # on any of the statement's lines (or the code line right below a
    # standalone pragma, which collect_pragmas resolved to the first one)
    spans: list[tuple[int, int]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.stmt) and getattr(node, "end_lineno", None):
            spans.append((node.lineno, node.end_lineno))

    # which pragma line(s) granted each (line, rule) — so stale-pragma can
    # tell exercised pragmas from rotting ones.  contributors mirrors
    # `allowed`, attributing each grant back to its source comment line.
    contributors: dict[int, dict[str, set[int]]] = {}
    src_lines = source.splitlines()
    for pln, names_raw in raw_pragmas:
        names = {n.strip() for n in names_raw.split(",") if n.strip()}
        covers = {pln}
        text = src_lines[pln - 1] if pln <= len(src_lines) else ""
        if text.lstrip().startswith("#"):    # standalone comment pragma
            nxt = pln
            while nxt < len(src_lines):
                stripped = src_lines[nxt].strip()
                if stripped and not stripped.startswith("#"):
                    covers.add(nxt + 1)
                    break
                nxt += 1
        for ln in covers:
            for name in names:
                contributors.setdefault(ln, {}).setdefault(
                    name, set()).add(pln)
    used_pragmas: set[tuple[int, str]] = set()

    def suppressed(rule_name: str, line: int) -> bool:
        lines = {line}
        best = None
        for s, e in spans:
            if s <= line <= e and (best is None
                                   or (e - s) < (best[1] - best[0])):
                best = (s, e)
        if best is not None:
            lines.update(range(best[0], best[1] + 1))
        hit = False
        for ln in lines:
            if rule_name in allowed.get(ln, ()):
                used_pragmas.update(
                    (p, rule_name)
                    for p in contributors.get(ln, {}).get(rule_name, ()))
                hit = True
        return hit

    seen: set[tuple] = set()
    for rule in get_rules(config):
        for v in rule.check(ctx):
            if suppressed(rule.name, v.line):
                continue
            key = (v.path, v.line, v.rule, v.message)
            if key in seen:
                continue   # nested defs are walked from both scopes
            seen.add(key)
            out.append(v)
    # stale-pragma: a pragma naming a known rule that suppressed nothing.
    # Only meaningful on a full run — under --select most rules never ran,
    # so their pragmas would all look stale.
    if not config.select:
        for pln, names_raw in raw_pragmas:
            for name in (n.strip() for n in names_raw.split(",")):
                if (name in rule_names and (pln, name) not in used_pragmas
                        and not suppressed("stale-pragma", pln)):
                    out.append(Violation(
                        path, pln, "stale-pragma",
                        f"pragma allow({name}) suppresses nothing — the "
                        f"violation it excused is gone; remove the pragma "
                        f"so the allowlist stays honest"))
    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out


def find_root(start: str) -> str:
    """Nearest ancestor of `start` containing pyproject.toml (else `start`)."""
    cur = os.path.abspath(start)
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    while True:
        if os.path.exists(os.path.join(cur, "pyproject.toml")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return os.path.abspath(start)
        cur = parent


def iter_py_files(target: str):
    if os.path.isfile(target):
        yield target
        return
    for dirpath, dirnames, filenames in os.walk(target):
        dirnames[:] = [d for d in dirnames
                       if d != "__pycache__" and not d.startswith(".")]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def run_paths(targets: list[str], config: Config | None = None,
              root: str | None = None):
    """Lint every .py file under `targets`. Paths in violations are relative
    to `root` (auto-detected via pyproject.toml when not given)."""
    root = os.path.abspath(root or find_root(targets[0] if targets else "."))
    config = config or Config()
    if not config.registered_markers:
        config = dataclasses.replace(
            config, registered_markers=load_registered_markers(root))
    out: list[Violation] = []
    for target in targets:
        for fp in iter_py_files(target):
            rel = os.path.relpath(os.path.abspath(fp), root).replace(
                os.sep, "/")
            if rel in EXCLUDED_FILES:
                continue
            try:
                with open(fp, encoding="utf-8") as f:
                    src = f.read()
            except (OSError, UnicodeDecodeError) as e:
                out.append(Violation(rel, 1, "unreadable", str(e)))
                continue
            out.extend(run_source(src, rel, config))
    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out
