"""Shared AST helpers: dotted-name resolution, a line-ordered device-value
tracker (the light intra-function dataflow the trace-hygiene rules run on),
and jit-wrapper discovery."""
from __future__ import annotations

import ast


def dotted(node: ast.AST) -> str | None:
    """`a.b.c` for a Name/Attribute chain, None for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_segment(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def call_name(call: ast.Call) -> str | None:
    return dotted(call.func)


def walk_skip_defs(node: ast.AST):
    """ast.walk that does NOT descend into nested function/class definitions
    (their bodies run at some other time — not under this lock / not in this
    trace)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                          ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))


# calls rooted here produce (or may produce) device arrays
DEVICE_ROOTS = ("jnp.", "jax.")
# ...except these: they return host values / metadata, or ARE the explicit
# device→host spelling
HOST_SAFE_CALLS = {
    "jax.device_get", "jax.device_count", "jax.local_device_count",
    "jax.devices", "jax.local_devices", "jax.process_count",
    "jax.process_index", "jax.default_backend", "jax.tree_util.keystr",
    "jnp.finfo", "jnp.iinfo", "jnp.dtype", "jnp.shape", "jnp.ndim",
    "jnp.issubdtype", "jax.eval_shape",
}
# attribute reads on a device value that are host metadata, never a sync
SAFE_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "itemsize",
              "nbytes", "at", "aval", "weak_type"}


def is_device_call(call: ast.Call, jit_names: set[str] | None = None) -> bool:
    """Does this call plausibly return a device array? jnp./jax. calls (minus
    the host-safe set), calls to `*_fn` attributes (the project's convention
    for jit-wrapped callables), and calls to known jit-created names."""
    name = call_name(call)
    if name is None:
        return False
    if name in HOST_SAFE_CALLS:
        return False
    if any(name.startswith(r) or name == r[:-1] for r in DEVICE_ROOTS):
        return True
    seg = name.rsplit(".", 1)[-1]
    if seg.endswith("_fn"):
        return True
    if jit_names and seg in jit_names:
        return True
    return False


class DeviceTracker:
    """Per-function, line-ordered tracking of which local names currently
    hold device values. Assignments from device-producing calls mark the
    targets; reassignment from host expressions clears them. Control flow is
    approximated by source order — good enough for a linter."""

    def __init__(self, func: ast.AST, jit_names: set[str] | None = None):
        self.jit_names = jit_names or set()
        # name -> sorted [(lineno, is_device)]
        self.assignments: dict[str, list[tuple[int, bool]]] = {}
        for node in walk_skip_defs(func):
            targets: list[ast.AST] = []
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                if node.value is not None:
                    targets, value = [node.target], node.value
            if value is None:
                continue
            dev = self._expr_is_device(value)
            for t in targets:
                for name in self._target_names(t):
                    self.assignments.setdefault(name, []).append(
                        (node.lineno, dev))
        for hist in self.assignments.values():
            hist.sort()

    @staticmethod
    def _target_names(t: ast.AST):
        if isinstance(t, ast.Name):
            yield t.id
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                yield from DeviceTracker._target_names(e)
        # attribute/subscript targets: not tracked (self._x is cross-function)

    def _expr_is_device(self, expr: ast.AST) -> bool:
        if isinstance(expr, ast.Call):
            return is_device_call(expr, self.jit_names)
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self._expr_is_device(e) for e in expr.elts)
        if isinstance(expr, (ast.BinOp,)):
            return (self._expr_is_device(expr.left)
                    or self._expr_is_device(expr.right))
        return False

    def is_device_at(self, name: str, lineno: int) -> bool:
        # strictly-earlier assignments only: in `x = np.asarray(x)` the use
        # on the right-hand side reads the PREVIOUS binding
        hist = self.assignments.get(name)
        if not hist:
            return False
        dev = False
        for ln, d in hist:
            if ln >= lineno:
                break
            dev = d
        return dev


def expr_mentions_device(expr: ast.AST, tracker: DeviceTracker,
                         parents: dict[ast.AST, ast.AST],
                         lineno: int) -> bool:
    """Does `expr` read a device value in a way that forces a host sync?
    Metadata access (.shape/.dtype/...), len(), and identity tests are
    shielded."""
    for node in ast.walk(expr):
        devicey = False
        if isinstance(node, ast.Call) and is_device_call(node,
                                                         tracker.jit_names):
            devicey = True
        elif isinstance(node, ast.Name) and tracker.is_device_at(node.id,
                                                                 lineno):
            devicey = True
        if not devicey:
            continue
        if not _is_shielded(node, expr, parents):
            return True
    return False


def _is_shielded(node: ast.AST, stop: ast.AST,
                 parents: dict[ast.AST, ast.AST]) -> bool:
    """Walk node→stop; a .shape/.dtype/... attribute read, a len()/
    isinstance()/getattr() call, or an `is`/`in` comparison anywhere on the
    path means the device value itself never crosses to the host."""
    cur = node
    while cur is not stop and cur is not None:
        parent = parents.get(cur)
        if isinstance(parent, ast.Attribute) and parent.attr in SAFE_ATTRS:
            return True
        if isinstance(parent, ast.Call):
            fname = dotted(parent.func)
            if cur is not parent.func and (
                    fname in ("len", "isinstance", "getattr", "hasattr",
                              "type", "id", "repr")
                    or fname in HOST_SAFE_CALLS):
                # jax.device_get IS the sanctioned explicit sync — a device
                # value inside it has already crossed the boundary on purpose
                return True
        if isinstance(parent, ast.Compare):
            ok = all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                     for op in parent.ops)
            if ok:
                return True
        cur = parent
    return False


def collect_jit_info(tree: ast.AST):
    """Scan a module for jit wrappings.

    Returns (jitted_funcs, jit_callables):
      jitted_funcs: {local function name: set of static/bound param names
                     (or positional indices as ints)} for functions defined
                     AND jit-wrapped in this module — the traced-branch rule
                     inspects their bodies.
      jit_callables: {assigned name (attr or local): static argnames} for
                     names bound to jax.jit(...) results — the jit-arg rule
                     checks calls to these.
    """
    jitted_funcs: dict[str, set] = {}
    jit_callables: dict[str, set[str]] = {}

    def static_names(call: ast.Call) -> set:
        out: set = set()
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and isinstance(n.value,
                                                                  str):
                        out.add(n.value)
            elif kw.arg == "static_argnums":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and isinstance(n.value,
                                                                  int):
                        out.add(n.value)
        return out

    def unwrap_target(fn_arg: ast.AST) -> tuple[str | None, set]:
        """(function name, extra static params) for jax.jit's first arg —
        follows partial(f, bound...) one level (bound args are fixed at
        wrap time → static)."""
        if isinstance(fn_arg, ast.Name):
            return fn_arg.id, set()
        if isinstance(fn_arg, ast.Call):
            fname = dotted(fn_arg.func)
            if fname in ("partial", "functools.partial") and fn_arg.args:
                inner = fn_arg.args[0]
                if isinstance(inner, ast.Name):
                    extra: set = set(range(1, len(fn_arg.args)))  # positions
                    extra.update(kw.arg for kw in fn_arg.keywords if kw.arg)
                    return inner.id, extra
        return None, set()

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and dotted(node.func) in ("jax.jit",
                                                                "jit"):
            statics = static_names(node)
            if node.args:
                fn_name, extra = unwrap_target(node.args[0])
                if fn_name:
                    jitted_funcs.setdefault(fn_name, set()).update(
                        statics | extra)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if dotted(dec) in ("jax.jit", "jit"):
                    jitted_funcs.setdefault(node.name, set())
                elif isinstance(dec, ast.Call) and dotted(dec.func) in (
                        "jax.jit", "jit", "partial", "functools.partial"):
                    inner = dec.args[0] if (dotted(dec.func) in
                                            ("partial", "functools.partial")
                                            and dec.args) else None
                    if dotted(dec.func) in ("jax.jit", "jit"):
                        jitted_funcs.setdefault(node.name, set()).update(
                            static_names(dec))
                    elif inner is not None and dotted(inner) in ("jax.jit",
                                                                 "jit"):
                        jitted_funcs.setdefault(node.name, set()).update(
                            static_names(dec))
    # second pass: names bound to jax.jit(...) results
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        if not (isinstance(v, ast.Call) and dotted(v.func) in ("jax.jit",
                                                               "jit")):
            continue
        statics = static_names(v)
        for t in node.targets:
            seg = last_segment(t)
            if seg:
                jit_callables[seg] = statics
    return jitted_funcs, jit_callables
