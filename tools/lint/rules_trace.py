"""Family (a): JAX trace/sync hygiene — hot-path host syncs and recompile
hazards. Scoped to the serving hot paths (engine/, ops/, models/): a stray
`.item()` there stalls the fused decode pipeline for every tenant, and one
tracer-dependent Python branch recompiles a program we promise compiles
exactly once (see the compile-count tripwire in localai_tpu/testing)."""
from __future__ import annotations

import ast

from tools.lint.astutil import (
    DeviceTracker, call_name, collect_jit_info, dotted, expr_mentions_device,
    is_device_call, last_segment,
)
from tools.lint.core import Violation


def _functions(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


class HostSyncItem:
    name = "host-sync-item"
    family = "trace"
    description = (".item() in a hot path — an implicit device→host sync "
                   "that stalls the decode pipeline")

    def check(self, ctx):
        if not ctx.config.in_hot_path(ctx.path):
            return
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item" and not node.args):
                yield Violation(
                    ctx.path, node.lineno, self.name,
                    ".item() forces a device→host sync; keep the value on "
                    "device, or jax.device_get() a batch of results once")


class HostSyncCast:
    name = "host-sync-cast"
    family = "trace"
    description = ("float()/int()/bool() on a device value in a hot path — "
                   "implicit device→host sync")

    def check(self, ctx):
        if not ctx.config.in_hot_path(ctx.path):
            return
        _, jit_callables = collect_jit_info(ctx.tree)
        jit_names = set(jit_callables)
        for fn in _functions(ctx.tree):
            tracker = DeviceTracker(fn, jit_names)
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id in ("float", "int", "bool")
                        and len(node.args) == 1):
                    continue
                if expr_mentions_device(node.args[0], tracker, ctx.parents,
                                        node.lineno):
                    yield Violation(
                        ctx.path, node.lineno, self.name,
                        f"{node.func.id}() on a device value blocks on the "
                        f"device — fetch once via jax.device_get() and cast "
                        f"the host copy")


class HostSyncAsarray:
    name = "host-sync-asarray"
    family = "trace"
    description = ("np.asarray()/np.array() on a device value in a hot "
                   "path — implicit device→host transfer")

    _NP = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}

    def check(self, ctx):
        if not ctx.config.in_hot_path(ctx.path):
            return
        _, jit_callables = collect_jit_info(ctx.tree)
        jit_names = set(jit_callables)
        for fn in _functions(ctx.tree):
            tracker = DeviceTracker(fn, jit_names)
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and call_name(node) in self._NP and node.args):
                    continue
                if expr_mentions_device(node.args[0], tracker, ctx.parents,
                                        node.lineno):
                    yield Violation(
                        ctx.path, node.lineno, self.name,
                        "np.asarray on a device value is an implicit "
                        "device→host transfer — spell the sync explicitly "
                        "with jax.device_get()")


class SyncBlockUntilReady:
    name = "sync-block-until-ready"
    family = "trace"
    description = ("block_until_ready() in a hot path — defeats the decode "
                   "pipeline (one in-flight dispatch)")

    def check(self, ctx):
        if not ctx.config.in_hot_path(ctx.path):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            is_method = (isinstance(node.func, ast.Attribute)
                         and node.func.attr == "block_until_ready")
            if name == "jax.block_until_ready" or is_method:
                yield Violation(
                    ctx.path, node.lineno, self.name,
                    "block_until_ready fences the dispatch pipeline; hot "
                    "paths must stay async — fence only in opt-in profiling "
                    "(telemetry/profiler) or startup probes")


class TracedBranch:
    name = "traced-branch"
    family = "trace"
    description = ("Python if/while on a jit-traced value — recompiles per "
                   "trace or raises TracerBoolConversionError")

    def check(self, ctx):
        jitted_funcs, _ = collect_jit_info(ctx.tree)
        if not jitted_funcs:
            return
        for fn in _functions(ctx.tree):
            statics = jitted_funcs.get(fn.name)
            if statics is None:
                continue
            args = ([a.arg for a in fn.args.posonlyargs]
                    + [a.arg for a in fn.args.args]
                    + [a.arg for a in fn.args.kwonlyargs])
            traced = set()
            for i, a in enumerate(args):
                if a in statics or i in statics:
                    continue
                # project conventions for non-array params
                if a in ("self", "cfg", "config", "mesh", "econfig"):
                    continue
                traced.add(a)
            if not traced:
                continue
            # propagate through straight-line assignments from traced values
            derived = set(traced)
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    mentions = any(
                        isinstance(n, ast.Name) and n.id in derived
                        and not _meta_only(n, node.value, ctx.parents)
                        for n in ast.walk(node.value))
                    if mentions:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                derived.add(t.id)
            for node in ast.walk(fn):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                hit = self._naked_traced_name(node.test, derived, ctx)
                if hit:
                    kind = "while" if isinstance(node, ast.While) else "if"
                    yield Violation(
                        ctx.path, node.lineno, self.name,
                        f"`{kind}` on traced value {hit!r} inside jitted "
                        f"{fn.name}() — use jnp.where/lax.cond, or mark the "
                        f"argument static")

    @staticmethod
    def _naked_traced_name(test: ast.AST, traced: set[str], ctx):
        from tools.lint.astutil import _is_shielded

        for n in ast.walk(test):
            if isinstance(n, ast.Name) and n.id in traced:
                if not _is_shielded(n, test, ctx.parents):
                    return n.id
        return None


def _meta_only(name_node, stop, parents):
    from tools.lint.astutil import _is_shielded

    return _is_shielded(name_node, stop, parents)


class JitArgRetrace:
    name = "jit-arg-retrace"
    family = "trace"
    description = ("argument type at a jit boundary defeats caching — lists/"
                   "generators retrace per length, bare len() retraces per "
                   "value")

    def check(self, ctx):
        _, jit_callables = collect_jit_info(ctx.tree)
        if not jit_callables:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            seg = last_segment(node.func)
            if seg not in jit_callables:
                continue
            statics = jit_callables[seg]
            candidates = [(None, a) for a in node.args] + [
                (kw.arg, kw.value) for kw in node.keywords
                if kw.arg not in statics]
            for kwname, arg in candidates:
                bad = self._bad_kind(arg)
                if bad:
                    where = f"keyword {kwname!r}" if kwname else "argument"
                    yield Violation(
                        ctx.path, arg.lineno, self.name,
                        f"{where} to jitted {seg!r} is {bad} — every "
                        f"distinct length/value compiles a new program; "
                        f"wrap in jnp.asarray / np.asarray or declare it "
                        f"in static_argnames")

    @staticmethod
    def _bad_kind(arg: ast.AST) -> str | None:
        if isinstance(arg, (ast.List, ast.ListComp, ast.Set, ast.SetComp,
                            ast.GeneratorExp)):
            return "a Python list/set/generator (variable-length pytree)"
        if isinstance(arg, ast.Call) and dotted(arg.func) == "len":
            return "a bare len() (a fresh Python int per call)"
        return None


class ShapeFromLen:
    name = "shape-from-len"
    family = "trace"
    description = ("array constructor shaped by len(data) in a hot path — "
                   "a data-dependent shape recompiles per request")

    _CTORS = {"zeros", "ones", "full", "empty", "arange"}

    def check(self, ctx):
        if not ctx.config.in_hot_path(ctx.path):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if not name or "." not in name:
                continue
            root, _, fn = name.rpartition(".")
            if root not in ("jnp", "jax.numpy") or fn not in self._CTORS:
                continue
            shape_args = list(node.args[:1]) + [
                kw.value for kw in node.keywords if kw.arg == "shape"]
            for arg in shape_args:
                for sub in ast.walk(arg):
                    if (isinstance(sub, ast.Call)
                            and dotted(sub.func) == "len"):
                        yield Violation(
                            ctx.path, node.lineno, self.name,
                            f"jnp.{fn} shaped by len(...) — pad to a fixed "
                            f"bucket instead (prefill_buckets pattern); "
                            f"data-dependent shapes recompile per request")
                        break


RULES = [HostSyncItem(), HostSyncCast(), HostSyncAsarray(),
         SyncBlockUntilReady(), TracedBranch(), JitArgRetrace(),
         ShapeFromLen()]
