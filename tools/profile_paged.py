"""On-chip paged-vs-dense decode breakdown (round-5: bench32 paged hit
91.7 tok/s vs 726.7 dense-16 — find the regression).

Times, at several slot counts on the real chip, ctx 1024, int8 KV:
  - ragged_decode_q8 attention alone: dense cache vs paged pool+table
  - full jitted decode_step: dense vs paged
  - the paged cache-write scatter alone (decode_step minus attention diff)

Usage: python tools/profile_paged.py [--slots 16,32] [--ctx 1024]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, n=20, warmup=3):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n * 1e3  # ms


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--slots", default="16,32")
    ap.add_argument("--ctx", type=int, default=1024)
    ap.add_argument("--size", default="8b")
    args = ap.parse_args()

    from bench import write_synthetic_checkpoint
    import tempfile

    os.environ["LOCALAI_ALLOW_SYNTHETIC"] = "1"
    from localai_tpu.engine.loader import load_config, load_params
    from localai_tpu.models.llama import decode_step, init_kv_cache
    from localai_tpu.ops.paged import BLOCK, init_paged
    from localai_tpu.ops.pallas import ragged_decode_q8
    from localai_tpu.ops.rope import rope_table

    tmp = tempfile.mkdtemp(prefix="profp-")
    ckpt = write_synthetic_checkpoint(args.size, tmp)
    cfg = load_config(ckpt, dtype="int8")
    params = load_params(ckpt, cfg, dtype="int8")
    jax.block_until_ready(params)
    dev = jax.devices()[0]
    print(f"device: {getattr(dev, 'device_kind', dev.platform)}")

    T = args.ctx
    maxb = T // BLOCK
    cos, sin = rope_table(cfg.rope, T)
    for B in [int(s) for s in args.slots.split(",")]:
        kc, vc = init_kv_cache(cfg, B, T, cache_type="int8")
        nblocks = B * maxb + 1
        pkc, pvc = init_paged(cfg.num_layers, nblocks, cfg.num_kv_heads,
                              cfg.head_dim, cache_type="int8")
        # identity-ish table: slot b's virtual block v -> physical 1+b*maxb+v
        table = (1 + np.arange(B)[:, None] * maxb
                 + np.arange(maxb)[None, :]).astype(np.int32)
        tab = jnp.asarray(table)
        lengths = jnp.full((B,), T - 8, jnp.int32)
        q = jnp.ones((B, 1, cfg.num_heads, cfg.head_dim), jnp.bfloat16)

        attn_d = jax.jit(lambda q, kq, ks, vq, vs, l:
                         ragged_decode_q8(q, kq, ks, vq, vs, l))
        ms_d = timeit(attn_d, q, kc.q[0], kc.s[0], vc.q[0], vc.s[0],
                      lengths, n=50)
        attn_p = jax.jit(lambda q, kq, ks, vq, vs, l, t:
                         ragged_decode_q8(q, kq, ks, vq, vs, l, table=t))
        ms_p = timeit(attn_p, q, pkc.q[0], pkc.s[0], pvc.q[0], pvc.s[0],
                      lengths, tab, n=50)
        print(f"[B={B:3d}] attn/layer dense {ms_d:6.3f} ms | paged {ms_p:6.3f}"
              f" ms | ratio {ms_p/ms_d:4.1f}x")

        tokens = jnp.zeros((B,), jnp.int32)
        active = jnp.ones((B,), bool)
        step_d = jax.jit(lambda p, t, l, kc, vc, a:
                         decode_step(p, cfg, t, l, cos, sin, kc, vc, a))
        ms_sd = timeit(step_d, params, tokens, lengths, kc, vc, active, n=20)
        step_p = jax.jit(lambda p, t, l, kc, vc, a, tb:
                         decode_step(p, cfg, t, l, cos, sin, kc, vc, a, tb))
        ms_sp = timeit(step_p, params, tokens, lengths, pkc, pvc, active,
                       tab, n=20)
        print(f"[B={B:3d}] decode_step dense {ms_sd:7.2f} ms "
              f"({B/ms_sd*1e3:6.0f} tok/s) | paged {ms_sp:7.2f} ms "
              f"({B/ms_sp*1e3:6.0f} tok/s) | ratio {ms_sp/ms_sd:4.1f}x")


if __name__ == "__main__":
    main()
