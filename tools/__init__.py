# makes `python -m tools.lint` / `python -m tools.regen_pb2` resolvable from
# the repo root; the profiling scripts in this directory stay runnable as
# plain `python tools/<script>.py` files
